"""Replica router tier: fleet-grade fault tolerance in front of N
``ModelServer`` replicas.

PR 11 made ONE engine crash-only (supervised restart, requeue-and-
resume, circuit breaker) — but the process stayed a single point of
failure: kill the server and every caller is stranded.  This module
is the robustness half of the serving fleet (ROADMAP item 2), in the
Podracer decoupled-dataflow mold (arXiv:2104.06272): a front tier
that treats replica death, slowness, and drain as ROUTINE SCHEDULING
EVENTS, with the co-tenancy tail pathologies of arXiv:2011.03641 as
the failure class the retry/hedging policy must never amplify.

- :class:`Replica` — one routed endpoint (URL in production,
  :class:`LocalReplica` spawns an in-process ``ModelServer`` fleet
  for tests/benches).  Per replica: outstanding-request count, the
  last health verdict, and a ``recovery.CircuitBreaker`` whose
  HALF_OPEN state admits exactly ONE live probe request
  (``try_probe``) before the replica re-enters rotation.
- :class:`ReplicaRouter` — probes ``GET /healthz`` on an interval
  (every probe socket carries an EXPLICIT timeout — the SOCKET-
  TIMEOUT rule: a timeout-less probe is how a hung replica wedges
  the router), parses the unified ``{"status", "reason"}`` schema
  (503 ``draining``/``engine_down`` -> out of rotation, recovery ->
  back in after a half-open success probe), and routes with
  least-outstanding load balancing plus RADIX-PREFIX AFFINITY: a
  request whose prompt extends a prefix registered via the router's
  ``/prefill`` goes to the replica whose radix store already holds
  it — unless that replica is saturated or unhealthy (affinity must
  NEVER beat health).
- FAILOVER, not client retries: a replica that dies mid-request gets
  the request replayed on a healthy replica as ``prompt ++
  tokens_received_so_far`` with ``resume_tokens`` (the cross-replica
  resume contract, docs/DESIGN.md — position-keyed RNG makes the
  resumed tokens bitwise identical per seed), governed by a global
  bounded :class:`RetryBudget` (token bucket: retries+hedges may
  never exceed a fraction of live traffic, so a sick fleet degrades
  to fast 503 ``retry_budget`` instead of a retry storm) with
  jittered backoff from the shared ``recovery.RetryPolicy``.
- HEDGING (optional): a request sitting past the p99 watermark fires
  a duplicate to a second replica; the first winner cancels the
  loser by closing its connection — the replica's client-disconnect
  probe cancels the request at its next step boundary (the PR 6
  cancel path), so a hedge never double-spends decode budget to
  completion.
- ROLLING RESTART: ``POST /fleet/restart`` drains one replica at a
  time (``/drain``, wait for in-flight zero, restart, re-admit via
  health probe) and never drops the ready count below
  ``min_ready``.  Requests shed by a drain race retry within budget
  — zero failed requests is the contract, pinned in
  tests/test_router.py.
- FLEET CHAOS: ``fleet_faults`` arms the seeded ``faults.FaultPlan``
  replica sites (``replica_kill`` / ``replica_hang`` /
  ``replica_slow``), polled once per routed request, so a fleet
  chaos run's fire pattern is a pure function of the plan.

Observability rides the existing surfaces: one ``router.stats()``
dict renders into ``GET /metrics`` (``ptpu_router_*`` gauges) and
``GET /info``, and ``X-Request-Id`` is forwarded replica-ward with a
replica-id prefix (``r0-<rid>`` — ``debug.format_replica_rid``) so
one request's history is traceable across a failover.

FLEET OBSERVABILITY (the cross-replica tier):

- Router-side REQUEST SPANS: every routed request leaves a causal
  record in a bounded ``debug.RequestHistory`` ring — the route
  decision (chosen replica + why: affinity / least-outstanding /
  half-open probe), every attempt with its send/receive bracket,
  failover replays with their ``resume_tokens`` count, hedge
  fire/win/cancel, and retry-budget denials.
- ``GET /fleet/requests/<id>`` STITCHES that router timeline with
  every involved replica's own ``GET /requests/<rN-id>`` record into
  ONE causal timeline: per-host monotonic clocks are reconciled by
  anchoring each replica segment at the router's SEND timestamp for
  that attempt and clamping it inside the send/receive bracket (a
  replica event can never appear to precede its own request or
  outlive its response — the causal-consistency pin in
  tests/test_fleet_observability.py).
- ``GET /fleet/metrics`` FEDERATES every replica's ``/metrics``:
  each series re-exported with a ``replica=`` label plus fleet
  rollups (``<name>_fleet{agg="sum"|"min"|"max"}``), so one scrape
  covers the tier.
- SLO BURN RATES: declared objectives (``--slo
  availability=99.9,ttft_p99_ms=1000``) are evaluated over a sliding
  window of the router's OWN accounting and exported as
  ``ptpu_router_slo_burn_rate{objective=}`` — burn 1.0 means the
  error budget is being spent exactly at the sustainable rate,
  burn >> 1 is the page.
"""

from __future__ import annotations

import http.client
import json
import logging
import re
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .debug import (RequestHistory, events_to_dicts,
                    format_replica_rid, new_request_id,
                    sanitize_request_id)
from .faults import FLEET_SITES, FaultPlan
from .forensics import ForensicsCore, compute_router_ledger
from .recovery import CircuitBreaker, RetryPolicy
from .telemetry import (LATENCY_BUCKETS, Histogram,
                        parse_prometheus_families, render_histogram)

__all__ = ["Replica", "LocalReplica", "ReplicaRouter", "RetryBudget",
           "SLOTracker", "make_router_server"]

logger = logging.getLogger(__name__)


# Structural no-drift contract (tests/test_fleet_observability.py):
# EVERY key of ReplicaRouter.stats() must render on /metrics under
# ``ptpu_router_<key>``, under a rename listed here, or carry an
# explicit exemption reason — a new router counter that skips the
# /metrics surface fails tier-1 instead of shipping dark.
STATS_METRIC_RENAMES = {
    "request_records_evicted":
        "ptpu_router_request_records_evicted_total",
    "rolling_restart": "ptpu_router_rolling_restart_in_progress",
    "fleet_faults_applied": "ptpu_router_fleet_faults_applied_total",
    # The probe-duration histogram's four stats keys all render
    # through ONE telemetry.render_histogram family.
    "probe_duration_buckets": "ptpu_router_probe_duration_seconds",
    "probe_duration_hist": "ptpu_router_probe_duration_seconds",
    "probe_duration_sum": "ptpu_router_probe_duration_seconds",
    "probe_duration_count": "ptpu_router_probe_duration_seconds",
    # The SLO block renders as the labeled burn-rate/target/violation
    # families.
    "slo": "ptpu_router_slo_burn_rate",
}
STATS_METRIC_EXEMPT = {
    "hedge": "config string; hedge activity rides hedges_*_total",
    "fleet_fault_stats": "plan-internal detail; applied counts "
                         "render via fleet_faults_applied_total",
}


_SLO_PCTL_RE = re.compile(r"^(ttft|latency)_p(\d{1,2}(?:\.\d+)?)_ms$")


class SLOTracker:
    """Declared service objectives evaluated over a sliding window of
    the router's own per-request accounting, exported as error-budget
    BURN RATES.

    Objectives (the ``--slo`` spec, comma-separated ``name=value``):

    - ``availability=99.9`` — at most 0.1% of requests may end 5xx
      (router sheds, deadline 504s, replica failures).  4xx client
      errors are EXCLUDED from the window: a bad request spends no
      error budget.
    - ``ttft_p99_ms=1000`` / ``latency_p99_ms=500`` — at most
      (100-99)=1% of COMPLETED requests may exceed the threshold.
      TTFT is client-visible from the router's vantage: the winning
      attempt's queue/hedge time at the router PLUS the replica's
      admission-anchored TTFT (the router injects ``timings`` into
      the forwarded request to read it; full latency stands in when
      a replica reports none).

    Burn rate = (violation rate over the window) / (error-budget
    rate): 1.0 means the budget is being spent exactly at the
    sustainable rate, 0 means no violations in the window, and a
    multi-window alerting stack pages on sustained burn >> 1 —
    Prometheus-side math the router now makes possible from its OWN
    accounting instead of bench-side reconstruction."""

    def __init__(self, objectives: Dict[str, float],
                 window: int = 512):
        if not objectives:
            raise ValueError("slo needs at least one objective")
        if window < 8:
            raise ValueError(
                f"slo window must be >= 8 requests; got {window}")
        self.objectives: Dict[str, Dict[str, float]] = {}
        for name, target in objectives.items():
            target = float(target)
            if name == "availability":
                if not 0.0 < target < 100.0:
                    raise ValueError(
                        f"availability target must be in (0, 100); "
                        f"got {target}")
                budget = (100.0 - target) / 100.0
                self.objectives[name] = {
                    "target": target, "budget": budget}
                continue
            m = _SLO_PCTL_RE.match(name)
            if m is None:
                raise ValueError(
                    f"unknown SLO objective {name!r} (supported: "
                    f"availability=<pct>, ttft_p<q>_ms=<ms>, "
                    f"latency_p<q>_ms=<ms>)")
            q = float(m.group(2))
            if not 0.0 < q < 100.0 or target <= 0:
                raise ValueError(
                    f"objective {name!r} needs 0 < percentile < 100 "
                    f"and a positive threshold; got {target}")
            self.objectives[name] = {
                "target": target, "metric": m.group(1),
                "budget": (100.0 - q) / 100.0}
        self._lock = threading.Lock()
        self._window: "deque[Dict[str, Any]]" = deque(maxlen=window)
        self.violations_total = {name: 0 for name in self.objectives}

    @staticmethod
    def parse(spec: str) -> Dict[str, float]:
        """``"availability=99.9,ttft_p99_ms=1000"`` -> objective
        dict.  Raises ValueError with the offending piece named."""
        out: Dict[str, float] = {}
        for piece in str(spec).split(","):
            piece = piece.strip()
            if not piece:
                continue
            name, sep, value = piece.partition("=")
            if not sep or not name.strip():
                raise ValueError(
                    f"SLO objective {piece!r} must be name=value")
            try:
                out[name.strip()] = float(value)
            except ValueError:
                raise ValueError(
                    f"SLO objective {piece!r} has a non-numeric "
                    f"target")
        if not out:
            raise ValueError(f"empty SLO spec {spec!r}")
        return out

    def observe(self, code: int, *, ttft_s: Optional[float],
                latency_s: float) -> None:
        """One terminal routed request.  4xx client errors are
        excluded entirely (they spend no budget and count in no
        window)."""
        if 400 <= code < 500:
            return
        ok = code == 200
        obs = {"ok": ok, "ttft": ttft_s if ok else None,
               "latency": latency_s if ok else None}
        with self._lock:
            self._window.append(obs)
            for name, o in self.objectives.items():
                if name == "availability":
                    if not ok:
                        self.violations_total[name] += 1
                else:
                    v = obs[o["metric"]]
                    if v is not None and v > o["target"] / 1e3:
                        self.violations_total[name] += 1

    def burn_rates(self) -> Dict[str, float]:
        with self._lock:
            window = list(self._window)
            out = {}
            for name, o in self.objectives.items():
                if name == "availability":
                    n = len(window)
                    bad = sum(1 for w in window if not w["ok"])
                else:
                    vals = [w[o["metric"]] for w in window
                            if w[o["metric"]] is not None]
                    n = len(vals)
                    bad = sum(1 for v in vals
                              if v > o["target"] / 1e3)
                rate = bad / n if n else 0.0
                out[name] = round(rate / o["budget"], 4)
            return out

    def stats(self) -> Dict[str, Any]:
        burns = self.burn_rates()
        with self._lock:
            n = len(self._window)
            return {
                "window": self._window.maxlen,
                "window_observations": n,
                "objectives": {
                    name: {"target": o["target"],
                           "burn_rate": burns[name],
                           "violations_total":
                               self.violations_total[name]}
                    for name, o in self.objectives.items()},
            }


def _attempt_record(att: "_Attempt", n: int, t0: float, *,
                    hedge: bool = False,
                    resume_n: int = 0) -> Dict[str, Any]:
    """ONE attempt-dict shape for every router record (/generate and
    /prefill paths both) — the stitcher keys on n/replica/send_ms/
    recv_ms, so the two paths must never diverge by hand."""
    def rel(t):
        return round(1e3 * (t - t0), 3) if t is not None else None

    return {
        "n": n,
        "replica": att.replica.id,
        "send_ms": rel(att.t_send),
        "recv_ms": rel(att.t_recv),
        "outcome": att.outcome() if att.done.is_set()
        else "abandoned",
        **({"code": att.code} if att.code is not None else {}),
        **({"hedge": True} if hedge else {}),
        **({"resume_tokens": resume_n} if resume_n else {}),
        **({"cancelled": True} if att.cancelled else {}),
    }


def _terminal_status(code: int) -> str:
    """The router record's terminal-status vocabulary — the SAME one
    the replica history uses (server.record_front's mapping), so
    ``GET /fleet/requests?status=`` filters read identically at both
    tiers."""
    if code == 200:
        return "complete"
    if code in (429, 503):
        return "shed"
    if code == 504:
        return "expired"
    if code == 499:
        return "cancelled"
    return "failed"


class RetryBudget:
    """Global bounded retry budget: a token bucket refilled by LIVE
    traffic.

    Every admitted request deposits ``ratio`` tokens (capped at
    ``burst``); every retry or hedge withdraws one.  The invariant —
    retries can never exceed ``ratio`` x live traffic plus the
    ``burst`` head start — is what keeps a sick fleet from
    amplifying itself into a retry storm (arXiv:2011.03641's
    concurrency-limit pathology applied to the router tier): when
    every replica is failing, the bucket drains and callers get FAST
    503 ``retry_budget`` instead of N x the load.  Counters are the
    pinned evidence (``tests/test_router.py``): ``withdrawals +
    denied`` exactly accounts for every retry decision ever made."""

    def __init__(self, ratio: float = 0.1, burst: float = 8.0):
        if ratio < 0:
            raise ValueError(f"retry ratio must be >= 0; got {ratio}")
        if burst < 1:
            raise ValueError(f"retry burst must be >= 1; got {burst}")
        self.ratio = float(ratio)
        self.burst = float(burst)
        self._level = float(burst)     # start full: a cold fleet must
        #                                be able to fail over at once
        self._lock = threading.Lock()
        self.deposits_total = 0.0
        self.withdrawals_total = 0
        self.denied_total = 0

    def on_request(self) -> None:
        """One live request admitted: deposit ``ratio`` tokens."""
        with self._lock:
            self._level = min(self.burst, self._level + self.ratio)
            self.deposits_total += self.ratio

    def try_spend(self) -> bool:
        """Withdraw one token for a retry/hedge; False = budget
        exhausted (the caller sheds fast instead of retrying)."""
        with self._lock:
            if self._level >= 1.0:
                self._level -= 1.0
                self.withdrawals_total += 1
                return True
            self.denied_total += 1
            return False

    def level(self) -> float:
        with self._lock:
            return round(self._level, 3)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"retry_budget_level": round(self._level, 3),
                    "retry_budget_ratio": self.ratio,
                    "retry_budget_burst": self.burst,
                    "retry_budget_spent_total": self.withdrawals_total,
                    "retry_budget_denied_total": self.denied_total}


class Replica:
    """One routed endpoint + its health state.

    The health machine mirrors ``recovery.CircuitBreaker`` semantics
    per replica: transport failures (probe or live) are "crashes";
    ``down_after`` of them inside the breaker window trips the
    replica OUT of rotation; after ``cooldown_s`` a healthy probe
    HALF-OPENs it, and exactly one live request (``breaker.
    try_probe``) — or a second consecutive healthy probe — closes it
    back IN.  A 503 from the replica itself (``reason: draining`` /
    ``engine_down`` — the unified /healthz schema) is an HONEST
    not-ready, tracked separately from crash suspicion: it clears
    the moment the replica answers 200 again, with no cooldown."""

    restartable = False

    def __init__(self, url: str, replica_id: str, *,
                 down_after: int = 2, window_s: float = 30.0,
                 cooldown_s: float = 1.0):
        parsed = urlparse(url if "//" in url else "http://" + url)
        if parsed.scheme not in ("http", ""):
            raise ValueError(
                f"replica URL must be http:// (got {url!r}; the "
                f"stdlib router tier does not speak TLS — put it "
                f"behind your ingress)")
        if not parsed.hostname or not parsed.port:
            raise ValueError(
                f"replica URL needs host:port (got {url!r})")
        self.host = parsed.hostname
        self.port = int(parsed.port)
        self.url = f"http://{self.host}:{self.port}"
        self.id = replica_id
        self.breaker = CircuitBreaker(
            threshold=down_after, window_s=window_s,
            cooldown_s=cooldown_s)
        self.health_ok = True          # optimistic until probed
        self.health_reason: Optional[str] = None
        self.draining = False          # router-side rotation latch
        #                                (rolling restart)
        self.consecutive_probe_failures = 0
        self.last_failure_t: Optional[float] = None
        # Wall time of the most recent /healthz probe (seconds): the
        # per-replica twin of the ptpu_router_probe_duration_seconds
        # histogram, so a slow-but-alive replica is identifiable in
        # rotation before it trips the hedge watermark.
        self.last_probe_s: Optional[float] = None
        # Disaggregated-serving role, learned from the /healthz 200
        # body ("prefill" / "decode" / "both"); optimistic "both"
        # until probed — an unprobed replica must stay routable.
        self.role = "both"
        # Per-link calibration (ROADMAP item 3): EWMA of the measured
        # wire throughput serving FROM this replica (completed
        # fetches + handoffs) and of its probe round-trip time.  None
        # until a measurement lands; shipped inside prefix hints so
        # the holder-side cost gate runs on observed link truth.
        self.wire_bytes_per_s: Optional[float] = None
        self.rtt_s: Optional[float] = None
        # Estimated host-clock skew vs the router (seconds, EWMA):
        # replica /healthz wall-clock minus the router's midpoint
        # wall-clock for the probe.  A host-clock ESTIMATE (error
        # bounded by the one-way delay asymmetry), exported as
        # ptpu_fleet_clock_skew_seconds{replica=} and used to flag
        # stitched-timeline segments whose silent skew correction
        # exceeds the suspect threshold.
        self.clock_skew_s: Optional[float] = None
        self.requests_total = 0
        self.failures_total = 0
        self._out_lock = threading.Lock()
        self.outstanding = 0

    # -- roles -----------------------------------------------------------

    def decode_capable(self) -> bool:
        return self.role in ("decode", "both")

    def prefill_capable(self) -> bool:
        return self.role in ("prefill", "both")

    # -- link calibration ------------------------------------------------

    _EWMA_ALPHA = 0.3

    # ptpu: lockfree[advisory EWMA gauge: a lost fold costs one sample of calibration accuracy, never correctness]
    def note_link_sample(self, nbytes: int, wall_s: float) -> None:
        """One completed transfer FROM this replica (wire fetch or
        handoff push): fold its observed bytes/s into the link EWMA.
        Tiny payloads are RTT-dominated and would drag the throughput
        estimate toward zero, so they only seed, never update."""
        if wall_s <= 0 or nbytes <= 0:
            return
        bps = nbytes / wall_s
        if self.wire_bytes_per_s is None:
            self.wire_bytes_per_s = bps
        elif nbytes >= 4096:
            a = self._EWMA_ALPHA
            self.wire_bytes_per_s = \
                a * bps + (1 - a) * self.wire_bytes_per_s

    def note_rtt_sample(self, rtt_s: float) -> None:
        """One probe round trip: the link RTT EWMA (the /healthz
        body is tiny, so probe wall time ~= RTT for this tier)."""
        if rtt_s <= 0:
            return
        if self.rtt_s is None:
            self.rtt_s = rtt_s
        else:
            a = self._EWMA_ALPHA
            self.rtt_s = a * rtt_s + (1 - a) * self.rtt_s

    def note_skew_sample(self, skew_s: float) -> None:
        """One probe's clock-skew estimate: replica /healthz ``t``
        minus the router's probe-midpoint wall clock, folded into
        the skew EWMA."""
        if self.clock_skew_s is None:
            self.clock_skew_s = skew_s
        else:
            a = self._EWMA_ALPHA
            self.clock_skew_s = \
                a * skew_s + (1 - a) * self.clock_skew_s

    def link_estimates(self) -> Dict[str, float]:
        """The measured-link keys a prefix hint carries (empty until
        a measurement exists — absent keys leave the holder-side
        policy on its static defaults)."""
        out: Dict[str, float] = {}
        if self.wire_bytes_per_s is not None:
            out["wire_bytes_per_s"] = round(self.wire_bytes_per_s, 1)
        if self.rtt_s is not None:
            out["rtt_s"] = round(self.rtt_s, 6)
        return out

    # -- rotation --------------------------------------------------------

    def eligible(self) -> bool:
        """In rotation for NORMAL routing (HALF_OPEN is handled by
        the router via ``breaker.try_probe`` — one live probe)."""
        return (not self.draining and self.health_ok
                and self.breaker.state == CircuitBreaker.CLOSED)

    def up(self) -> bool:
        """The readiness gauge (``ptpu_router_replica_up``) and the
        rolling restart's min-ready accounting."""
        return self.eligible()

    # ptpu: lockfree[advisory failure stats: the breaker serializes real state under its own lock; these feed metrics]
    def note_failure(self, now: Optional[float] = None) -> None:
        """Transport-level evidence against this replica (probe or
        live request): feeds the breaker."""
        self.failures_total += 1
        self.last_failure_t = time.monotonic() if now is None else now
        self.breaker.record_crash(self.last_failure_t)

    def note_success(self) -> None:
        self.breaker.record_success()

    def maybe_half_open(self) -> None:
        """A healthy probe on an OPEN breaker: half-open once the
        cooldown since the last failure has elapsed (the supervisor's
        cooldown-then-probe cycle, router-side)."""
        if self.breaker.state != CircuitBreaker.OPEN:
            return
        last = self.last_failure_t
        if last is None or time.monotonic() - last \
                >= self.breaker.cooldown_s:
            self.breaker.half_open()

    # -- accounting ------------------------------------------------------

    def inc_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding += 1
            self.requests_total += 1

    def dec_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding = max(0, self.outstanding - 1)

    # -- chaos hooks (LocalReplica implements; URL replicas are not
    #    controllable from here) ----------------------------------------

    def chaos_kill(self) -> bool:
        return False

    def chaos_hang(self) -> bool:
        return False

    def chaos_slow(self, delay_s: float) -> bool:
        return False

    def restart(self) -> None:
        raise RuntimeError(
            f"replica {self.id} ({self.url}) is not restartable "
            f"from this router (URL replicas restart via their own "
            f"orchestrator; drain it with POST {self.url}/drain)")

    def describe(self) -> Dict[str, Any]:
        return {
            "id": self.id, "url": self.url,
            "up": self.up(),
            "state": ("draining" if self.draining
                      else self.breaker.state if not self.health_ok
                      or self.breaker.state != CircuitBreaker.CLOSED
                      else "up"),
            "breaker": self.breaker.state,
            **({"health_reason": self.health_reason}
               if self.health_reason else {}),
            "outstanding": self.outstanding,
            "role": self.role,
            **self.link_estimates(),
            "consecutive_probe_failures":
                self.consecutive_probe_failures,
            **({"last_probe_s": self.last_probe_s}
               if self.last_probe_s is not None else {}),
            **({"clock_skew_s": round(self.clock_skew_s, 6)}
               if self.clock_skew_s is not None else {}),
            "requests_total": self.requests_total,
            "failures_total": self.failures_total,
        }


class _ChaosHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with replica-level chaos hooks: ``killed``
    (connections closed unanswered, listener down), ``hang_event``
    (connections accepted, held silently — the probe-timeout
    pathology), ``slow_s`` (every request slow-walked — the tail
    pathology hedging absorbs).  Tracks live client sockets so
    ``kill`` can reset in-flight connections the way a process death
    would."""

    request_queue_size = 128
    daemon_threads = True

    def __init__(self, addr, handler):
        super().__init__(addr, handler)
        self.killed = False
        self.hang_event = threading.Event()
        self.slow_s = 0.0
        self._conn_lock = threading.Lock()
        self._conns: set = set()

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def finish_request(self, request, client_address):
        if self.killed:
            return                      # closed unanswered
        while self.hang_event.is_set() and not self.killed:
            # Hold the connection open, serve nothing: the router's
            # EXPLICIT socket timeouts are what keep this from
            # wedging anything upstream.
            time.sleep(0.02)
        if self.killed:
            return
        if self.slow_s > 0.0:
            time.sleep(self.slow_s)
        super().finish_request(request, client_address)

    def reset_connections(self) -> None:
        with self._conn_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class LocalReplica(Replica):
    """An in-process replica: spawns a ``ModelServer`` from
    ``factory`` behind a chaos-capable HTTP server on a local port.
    The test/bench fleet substrate — and the restart hook the rolling
    restart drives.  ``factory()`` must return a fresh
    ``ModelServer`` (it is called again on ``restart``)."""

    restartable = True

    def __init__(self, factory: Callable[[], Any], replica_id: str,
                 *, host: str = "127.0.0.1", **kw):
        self.factory = factory
        self._spawn_host = host
        self.ms = factory()
        self.srv = _ChaosHTTPServer((host, 0), _replica_handler(
            self.ms))
        self._serve_thread = threading.Thread(
            target=self.srv.serve_forever, daemon=True,
            name=f"replica-{replica_id}")
        self._serve_thread.start()
        port = self.srv.server_address[1]
        super().__init__(f"http://{host}:{port}", replica_id, **kw)

    # -- chaos -----------------------------------------------------------

    def chaos_kill(self) -> bool:
        """Process-death simulation: listener closed (new connections
        refused), in-flight connections reset unanswered, engine
        stopped.  ``restart`` brings a fresh server up on the SAME
        port."""
        self.srv.killed = True
        self.srv.shutdown()
        self.srv.server_close()
        self.srv.reset_connections()
        try:
            self.ms.close()
        except Exception:
            logger.debug("replica %s kill: ModelServer close failed",
                         self.id, exc_info=True)
        return True

    def chaos_hang(self) -> bool:
        self.srv.hang_event.set()
        return True

    def chaos_unhang(self) -> bool:
        self.srv.hang_event.clear()
        return True

    def chaos_slow(self, delay_s: float) -> bool:
        self.srv.slow_s = float(delay_s)
        return True

    def restart(self) -> None:
        """Fresh ``ModelServer`` + HTTP server on the same port (the
        rolling-restart unit).  Also the recovery path after
        ``chaos_kill``."""
        if not self.srv.killed:
            # A live server restarting in place: take the old one
            # down first (the rolling restart drained it already).
            self.srv.killed = True
            self.srv.shutdown()
            self.srv.server_close()
            self.srv.reset_connections()
            try:
                self.ms.close()
            except Exception:
                logger.debug(
                    "replica %s restart: old ModelServer close "
                    "failed", self.id, exc_info=True)
        self.ms = self.factory()
        self.srv = _ChaosHTTPServer((self._spawn_host, self.port),
                                    _replica_handler(self.ms))
        self._serve_thread = threading.Thread(
            target=self.srv.serve_forever, daemon=True,
            name=f"replica-{self.id}")
        self._serve_thread.start()

    def close(self) -> None:
        try:
            self.srv.killed = True
            self.srv.shutdown()
            self.srv.server_close()
            self.srv.reset_connections()
        except Exception:
            logger.debug("replica %s close: HTTP server teardown "
                         "failed", self.id, exc_info=True)
        try:
            self.ms.close()
        except Exception:
            logger.debug("replica %s close: ModelServer close "
                         "failed", self.id, exc_info=True)


def _replica_handler(ms):
    """The ModelServer's own HTTP handler class, mounted on the
    chaos-capable server instead of make_server's plain one."""
    from .server import make_handler

    return make_handler(ms)


class _Attempt:
    """One in-flight forwarded request: its own connection (with an
    EXPLICIT timeout), its own thread, and a cancel that closes the
    socket — which IS the replica-side cancel path (the client-
    disconnect probe evicts the request at the next step boundary,
    PR 6)."""

    def __init__(self, replica: Replica, method: str, path: str,
                 body: bytes, headers: Dict[str, str],
                 timeout_s: float):
        self.replica = replica
        self.method = method
        self.path = path
        self.body = body
        self.headers = headers
        self.timeout_s = max(0.05, float(timeout_s))
        self.done = threading.Event()
        self.code: Optional[int] = None
        self.resp: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False
        # Send/receive bracket (monotonic): the router-side causal
        # anchor the /fleet/requests stitcher reconciles each
        # replica's own clock against — a replica event for this
        # attempt can only have happened inside [t_send, t_recv].
        self.t_send: Optional[float] = None
        self.t_recv: Optional[float] = None
        self._conn: Optional[http.client.HTTPConnection] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "_Attempt":
        self.replica.inc_outstanding()
        self.t_send = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"route-{self.replica.id}")
        self._thread.start()
        return self

    def _run(self) -> None:
        conn = None
        try:
            conn = http.client.HTTPConnection(
                self.replica.host, self.replica.port,
                timeout=self.timeout_s)
            self._conn = conn
            conn.request(self.method, self.path, self.body,
                         self.headers)
            r = conn.getresponse()
            data = r.read()
            self.code = r.status
            try:
                self.resp = json.loads(data)
            except (ValueError, TypeError):
                self.resp = {"error": "replica returned a non-JSON "
                                      "body"}
        except BaseException as e:  # transport verdicts, incl. timeout
            self.error = e
        finally:
            self.t_recv = time.monotonic()
            self.replica.dec_outstanding()
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            self.done.set()

    def cancel(self) -> None:
        """First-winner-cancels: closing the connection delivers the
        replica-side cancel (the disconnect probe — PR 6), so the
        loser stops burning decode budget at its next boundary."""
        self.cancelled = True
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    # -- outcome classification -----------------------------------------

    RETRYABLE_REASONS = frozenset({"draining", "engine_down"})

    def outcome(self) -> str:
        """``ok`` | ``retryable`` | ``terminal`` — the router's whole
        decision space.  Retryable: transport death (connect
        refused/reset, read timeout — a dead or hung replica), 429
        (that replica's queue is full; another may be idle), and the
        replica-level 503s (``draining``/``engine_down``).  Terminal:
        everything else — 400s, poisoned convictions, deterministic
        sheds (``kv_pages`` fails identically fleet-wide; retrying it
        amplifies load for nothing)."""
        if self.error is not None:
            return "retryable"
        if self.code == 200:
            return "ok"
        if self.code == 429:
            return "retryable"
        if self.code == 503:
            reason = (self.resp or {}).get("reason")
            if reason in self.RETRYABLE_REASONS:
                return "retryable"
        return "terminal"


class ReplicaRouter:
    """The front tier: owns N replicas, probes their health, routes
    with least-outstanding + prefix affinity, fails over with a
    bounded retry budget, hedges stragglers, and rolls restarts.
    See the module docstring for the full design."""

    def __init__(self, replicas: List, *,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 down_after: int = 2,
                 cooldown_s: float = 1.0,
                 retry_ratio: float = 0.1,
                 retry_burst: float = 8.0,
                 max_attempts: int = 3,
                 retry_policy: Optional[RetryPolicy] = None,
                 request_timeout_s: float = 120.0,
                 hedge: str = "off",
                 hedge_min_s: float = 0.2,
                 affinity: bool = True,
                 affinity_max_outstanding: int = 8,
                 affinity_entries: int = 64,
                 prefix_handoff: bool = True,
                 disagg_min_tokens: int = 16,
                 rebalance_every_s: float = 0.0,
                 min_ready: int = 1,
                 fleet_faults=None,
                 request_history: int = 256,
                 slo=None,
                 slo_window: int = 512,
                 forensics: bool = True,
                 forensics_dir: Optional[str] = None,
                 sentry_window: int = 64,
                 sentry_baseline_windows: int = 4,
                 clock_skew_suspect_s: float = 0.25,
                 autostart: bool = True):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas: List[Replica] = []
        for i, r in enumerate(replicas):
            if isinstance(r, Replica):
                self.replicas.append(r)
            else:
                r = Replica(str(r), f"r{i}")
                self.replicas.append(r)
            # The ROUTER owns rotation policy: its down_after /
            # cooldown_s knobs configure every replica's breaker,
            # constructed or passed (a passed Replica's ctor-default
            # breaker silently overriding the router's knobs was a
            # real config trap — the test/bench fleets all pass
            # instances).
            r.breaker = CircuitBreaker(
                threshold=down_after, window_s=r.breaker.window_s,
                cooldown_s=cooldown_s)
        ids = [r.id for r in self.replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        if probe_interval_s <= 0 or probe_timeout_s <= 0:
            raise ValueError(
                f"probe_interval_s and probe_timeout_s must be > 0; "
                f"got {probe_interval_s}, {probe_timeout_s}")
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1; got "
                             f"{max_attempts}")
        if request_timeout_s <= 0:
            raise ValueError(f"request_timeout_s must be > 0; got "
                             f"{request_timeout_s}")
        if min_ready < 0:
            raise ValueError(f"min_ready must be >= 0; got "
                             f"{min_ready}")
        if disagg_min_tokens < 1:
            raise ValueError(f"disagg_min_tokens must be >= 1; got "
                             f"{disagg_min_tokens}")
        if rebalance_every_s < 0:
            raise ValueError(f"rebalance_every_s must be >= 0; got "
                             f"{rebalance_every_s}")
        if hedge != "off" and hedge != "p99":
            try:
                float(hedge)
            except (TypeError, ValueError):
                raise ValueError(
                    f"hedge must be 'off', 'p99', or a threshold in "
                    f"seconds; got {hedge!r}")
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.max_attempts = int(max_attempts)
        self.budget = RetryBudget(retry_ratio, retry_burst)
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy(max_attempts=max_attempts,
                             base_delay_s=0.02, max_delay_s=0.5)
        self.hedge = hedge
        self.hedge_min_s = float(hedge_min_s)
        self.affinity_enabled = bool(affinity)
        self.affinity_max_outstanding = int(affinity_max_outstanding)
        # Drain-time cache migration (POST /prefix/handoff before a
        # rolling-restart flush).  Off = the seed per-replica-only
        # behavior: a restart is a cache flush.
        self.prefix_handoff_enabled = bool(prefix_handoff)
        # Disaggregated serving: prompts at or above this length get
        # the two-stage prefill->decode schedule when a dedicated
        # prefill tier exists.  Below it the remote-prefill round
        # trip costs more than decoding the prefill locally (same
        # calculus as PrefixFetchPolicy.min_tokens, and the same
        # default).
        self.disagg_min_tokens = int(disagg_min_tokens)
        # Optional cadence for POST /fleet/prefix/rebalance driven
        # off the federated kv_host_* gauges.  0 (default) = operator
        # trigger only, the PR 16 behavior.  One-flight: the cadence
        # thread and an operator POST share the same non-blocking
        # lock, so a slow pass is skipped, never stacked.
        self.rebalance_every_s = float(rebalance_every_s)
        self._rebalance_flight = threading.Lock()
        self._rebalance_thread: Optional[threading.Thread] = None
        self.min_ready = int(min_ready)
        self.fleet_faults = FaultPlan.load(fleet_faults) \
            if fleet_faults is not None else None
        self.draining = False
        # Router-side request spans: the bounded terminal-record ring
        # behind GET /fleet/requests — the SAME RequestHistory
        # machinery each replica runs (serving/debug.py), holding the
        # router's half of a request's causal story (route decisions,
        # attempt brackets, failovers, hedges, budget denials).
        # 0 disables the layer, one attribute check per request.
        self.history = RequestHistory(request_history)
        # ROUTER-SIDE FORENSICS (serving/forensics.py): the router's
        # own phase accumulator + anomaly sentry over its ledger
        # phases (route pick, attempt brackets, remote prefill,
        # retry backoff) — GET /fleet/anomalies merges its findings
        # with every replica's GET /anomalies.
        self.forensics: Optional[ForensicsCore] = None
        if forensics:
            self.forensics = ForensicsCore(
                window=sentry_window,
                baseline_windows=sentry_baseline_windows,
                out_dir=forensics_dir,
                snapshot_fn=self.stats,
                record_fn=self.history.get)
        # Stitched-timeline segments whose estimated replica clock
        # skew exceeds this get flagged ``clock_skew_suspect`` —
        # the silent anchor correction stops hiding a bad clock.
        self.clock_skew_suspect_s = float(clock_skew_suspect_s)
        # Per-probe wall-time histogram: a slow-but-alive replica is
        # visible in rotation BEFORE it trips the hedge watermark.
        self.probe_hist = Histogram(LATENCY_BUCKETS)
        # SLO layer: declared objectives evaluated over a sliding
        # window of the router's own accounting (burn-rate gauges).
        if slo is None:
            self.slo: Optional[SLOTracker] = None
        elif isinstance(slo, SLOTracker):
            self.slo = slo
        else:
            self.slo = SLOTracker(
                SLOTracker.parse(slo) if isinstance(slo, str)
                else dict(slo),
                window=int(slo_window))
        # Prefix-affinity map: registered-prefix token tuple ->
        # ORDERED holder list (primary first), LRU-bounded.
        # Router-side mirror of what the replicas' radix stores
        # hold; longest-match by scan (the registered-prefix
        # population is small — system prompts).  Secondary holders
        # accumulate from drain handoffs and observed wire fetches,
        # so failover and the fetch hint both have somewhere to go
        # when the primary leaves rotation.
        self._affinity: "OrderedDict[Tuple[int, ...], List[str]]" \
            = OrderedDict()
        self._affinity_cap = int(affinity_entries)
        self._affinity_lock = threading.Lock()
        # Latency window for the hedge watermark (the engine's
        # sliding-p99 idiom: recent observations, never the
        # cumulative histogram).
        self._lat_recent: "deque[float]" = deque(maxlen=64)
        self._lat_lock = threading.Lock()
        # Counters (one stats() dict -> /metrics + /info, no drift).
        self._stats_lock = threading.Lock()
        self.requests_total = 0
        self.completed_total = 0
        self.errors_total = 0
        self.shed_total = 0            # router-level fast 503s
        self.failovers_total = 0
        self.resumed_tokens_total = 0
        self.resumes_total = 0         # failovers replayed WITH
        #                                partial output
        self.hedges_fired_total = 0
        self.hedges_won_total = 0
        self.hedges_cancelled_total = 0
        # Metrics federation (GET /fleet/metrics): scrape accounting.
        self.fleet_scrapes_total = 0
        self.fleet_scrape_errors_total = 0
        # Fleet prefix cache (the kv_fleet_* family): hint
        # injections, observed wire fetches, drain handoffs, and the
        # one-copy-somewhere rebalance pass.
        self.kv_fleet_hints_injected_total = 0
        self.kv_fleet_wire_fetches_total = 0
        self.kv_fleet_handoffs_total = 0
        self.kv_fleet_handoff_entries_total = 0
        self.kv_fleet_handoff_failed_total = 0
        self.kv_fleet_rebalances_total = 0
        self.kv_fleet_evict_hints_total = 0
        # Cadenced rebalance (--rebalance-every): runs attempted /
        # failed (operator-triggered passes count only in
        # kv_fleet_rebalances_total, as before).
        self.kv_fleet_rebalance_runs_total = 0
        self.kv_fleet_rebalance_failed_total = 0
        # Disaggregated serving: two-stage schedules taken, and
        # stage-1 (remote prefill) failures degraded to decode-side
        # re-prefill — the counted-never-fatal rung of the ladder.
        self.disagg_prefills_total = 0
        self.disagg_prefill_failed_total = 0
        self.disagg_handoffs_total = 0
        self.fleet_faults_applied: Dict[str, int] = {}
        self._rr = 0                   # least-outstanding tiebreak
        # Rolling restart state (one at a time; POST /fleet/restart).
        # ``restart_state["completed"]`` is per-RUN progress (resets
        # each restart); ``restarts_completed_total`` is the
        # monotonic counter /metrics exports — a Prometheus counter
        # must never go backwards.
        self._restart_lock = threading.Lock()
        self.restarts_completed_total = 0
        self.restart_state: Dict[str, Any] = {
            "in_progress": False, "completed": 0, "rounds_total": 0,
            "last_error": None, "min_ready_floor_observed": None}
        self._stop = False
        self._probe_thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._probe_thread is not None \
                and self._probe_thread.is_alive():
            return
        self._stop = False
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="router-probe")
        self._probe_thread.start()
        if self.rebalance_every_s > 0 and (
                self._rebalance_thread is None
                or not self._rebalance_thread.is_alive()):
            self._rebalance_thread = threading.Thread(
                target=self._rebalance_loop, daemon=True,
                name="router-rebalance")
            self._rebalance_thread.start()

    def close(self) -> None:
        self._stop = True
        t = self._probe_thread
        if t is not None:
            t.join(timeout=self.probe_timeout_s
                   * max(2, len(self.replicas)) + 5)
        t = self._rebalance_thread
        if t is not None:
            t.join(timeout=self.probe_timeout_s + 5)

    def drain(self) -> Dict[str, Any]:
        """Router-level drain: stop admitting (503 ``draining``) —
        the replicas keep running; drain them individually or via
        the rolling restart."""
        self.draining = True
        return {"draining": True}

    # -- health probing --------------------------------------------------

    def _http_text(self, replica: Replica, method: str, path: str,
                   *, body: Optional[bytes] = None,
                   timeout_s: Optional[float] = None
                   ) -> Tuple[Optional[int], bytes]:
        """One bounded HTTP exchange with a replica: ``(status, raw
        body)``, or ``(None, b"")`` on transport failure.  The ONE
        copy of the connect/request/read/close sequence the probe,
        drain, re-admission, federation-scrape, and request-stitch
        paths share (every connection carries an explicit timeout —
        SOCKET-TIMEOUT)."""
        conn = None
        try:
            conn = http.client.HTTPConnection(
                replica.host, replica.port,
                timeout=timeout_s if timeout_s is not None
                else self.probe_timeout_s)
            conn.request(method, path, body,
                         {"Content-Type": "application/json"}
                         if body is not None else {})
            r = conn.getresponse()
            return r.status, r.read()
        except (OSError, http.client.HTTPException):
            return None, b""
        finally:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _http_json(self, replica: Replica, method: str, path: str,
                   *, body: Optional[bytes] = None
                   ) -> Tuple[Optional[int], Dict[str, Any]]:
        """:meth:`_http_text` with the body parsed as a JSON dict
        (non-dict / non-JSON bodies parse to ``{}``)."""
        status, raw = self._http_text(replica, method, path,
                                      body=body)
        if status is None:
            return None, {}
        try:
            parsed = json.loads(raw)
            if not isinstance(parsed, dict):
                parsed = {}
        except (ValueError, TypeError):
            parsed = {}
        return status, parsed

    def _probe_once(self, replica: Replica) -> None:
        """One /healthz probe.  200 -> healthy (half-open/close the
        breaker per the recovery semantics); 503 with the unified
        schema -> honest not-ready; transport failure -> crash
        evidence.  Every probe's wall time feeds the
        ``ptpu_router_probe_duration_seconds`` histogram and the
        replica's ``last_probe_s`` — the early-warning surface for a
        slow-but-alive replica (a probe that takes 800ms of a 2s
        timeout is a replica already hurting, still in rotation)."""
        t0 = time.monotonic()
        t0_wall = time.time()
        status, parsed = self._http_json(replica, "GET", "/healthz")
        dt = time.monotonic() - t0
        self.probe_hist.observe(dt)
        replica.last_probe_s = round(dt, 6)
        if status is None:
            replica.consecutive_probe_failures += 1
            replica.health_ok = False
            replica.health_reason = "unreachable"
            replica.note_failure()
            return
        replica.consecutive_probe_failures = 0
        # Any completed exchange is an RTT sample for the link
        # calibration EWMA (a /healthz round trip is all overhead —
        # exactly what the wire-fetch cost gate's rtt term models).
        replica.note_rtt_sample(dt)
        if status == 200:
            replica.health_ok = True
            replica.health_reason = None
            # Role discovery: /healthz advertises the replica's
            # serving role, so the router learns the fleet's shape
            # from the same probe that learns its health.  Replicas
            # predating the role field read as "both" (monolithic).
            role = parsed.get("role")
            if role in ("prefill", "decode", "both"):
                replica.role = role
            # Clock-skew ESTIMATE: the replica stamps its /healthz
            # 200 body with its wall clock; against the router's
            # probe-midpoint wall clock that bounds the skew to the
            # one-way delay asymmetry.  Host clocks only — labeled
            # an estimate everywhere it surfaces (the PR 9
            # time-truth discipline).
            rt = parsed.get("t")
            if isinstance(rt, (int, float)) \
                    and not isinstance(rt, bool):
                replica.note_skew_sample(
                    float(rt) - (t0_wall + dt / 2.0))
            st = replica.breaker.state
            if st == CircuitBreaker.OPEN:
                replica.maybe_half_open()
            elif st == CircuitBreaker.HALF_OPEN:
                # Second consecutive healthy probe: the half-open
                # success probe an idle fleet needs (live traffic
                # closes it sooner via try_probe + success).
                replica.note_success()
        else:
            replica.health_ok = False
            replica.health_reason = parsed.get(
                "reason", parsed.get("status", f"http_{status}"))

    def _probe_loop(self) -> None:
        while not self._stop:
            for replica in self.replicas:
                if self._stop:
                    return
                self._probe_once(replica)
            deadline = time.monotonic() + self.probe_interval_s
            while not self._stop and time.monotonic() < deadline:
                time.sleep(0.02)

    # -- affinity --------------------------------------------------------

    def _note_prefix(self, toks: Tuple[int, ...],
                     replica_id: str, *,
                     primary: bool = True) -> None:
        """Record ``replica_id`` as a holder of ``toks``.  Primary
        holders (a routed /prefill, a handoff successor) lead the
        list; secondary holders (an observed wire fetch — the
        fetcher keeps a host-tier copy) append behind them."""
        with self._affinity_lock:
            holders = self._affinity.get(toks)
            if holders is None:
                holders = self._affinity[toks] = []
            if replica_id in holders:
                if primary and holders[0] != replica_id:
                    holders.remove(replica_id)
                    holders.insert(0, replica_id)
            elif primary:
                holders.insert(0, replica_id)
            else:
                holders.append(replica_id)
            self._affinity.move_to_end(toks)
            while len(self._affinity) > self._affinity_cap:
                self._affinity.popitem(last=False)

    def _affinity_holders(self, prompt: Optional[List[int]]
                          ) -> List[str]:
        """ORDERED holder list (primary first) for the LONGEST
        registered prefix of this prompt — empty when none."""
        if not self.affinity_enabled or not prompt:
            return []
        best_len, best = 0, []
        with self._affinity_lock:
            for toks, holders in self._affinity.items():
                n = len(toks)
                if n > best_len and n <= len(prompt) \
                        and list(toks) == prompt[:n]:
                    best_len, best = n, list(holders)
        return best

    def _affinity_for(self, prompt: Optional[List[int]]
                      ) -> Optional[str]:
        """The PRIMARY holder of the longest registered prefix of
        this prompt, or None."""
        holders = self._affinity_holders(prompt)
        return holders[0] if holders else None

    def _affinity_replace(self, old_id: str,
                          new_id: Optional[str]) -> None:
        """Re-point every holder entry from ``old_id`` to ``new_id``
        (drain handoff succeeded: the successor now holds what the
        drainee held), or drop ``old_id`` everywhere when ``new_id``
        is None (handoff failed: the restart flushes the drainee's
        store, so the stale binding must not attract traffic)."""
        with self._affinity_lock:
            for toks in list(self._affinity):
                holders = self._affinity[toks]
                if old_id not in holders:
                    continue
                holders.remove(old_id)
                if new_id is not None and new_id not in holders:
                    holders.append(new_id)
                if not holders:
                    del self._affinity[toks]

    # -- replica selection -----------------------------------------------

    def _pick(self, prompt: Optional[List[int]],
              exclude: set, want: str = "any"
              ) -> Tuple[Optional[Replica], str]:
        """``(replica, why)``: least-outstanding among in-rotation
        replicas, with prefix affinity as a PREFERENCE — the affinity
        replica wins only while it is healthy and below the
        saturation bound (affinity must never beat health, pinned).
        ``why`` is the route-decision tag the request-span record
        carries: ``affinity`` / ``least_outstanding`` /
        ``half_open_probe`` / ``none``.

        ``want`` is the role-split capability filter.  ``"decode"``
        is HARD: a role='prefill' replica rejects /generate outright,
        so routing one there just burns an attempt.  ``"prefill"``
        is SOFT — every role physically serves /prefill (a decode
        replica's re-prefill fallback depends on it) — so it narrows
        to prefill-capable replicas only while at least one is in
        rotation."""
        eligible = [r for r in self.replicas
                    if r.id not in exclude and r.eligible()]
        half_open = [r for r in self.replicas
                     if r.id not in exclude and not r.draining
                     and r.health_ok
                     and r.breaker.state == CircuitBreaker.HALF_OPEN]
        if want == "decode":
            eligible = [r for r in eligible if r.decode_capable()]
            half_open = [r for r in half_open if r.decode_capable()]
        elif want == "prefill":
            pref = [r for r in eligible if r.prefill_capable()]
            if pref:
                eligible = pref
                half_open = [r for r in half_open
                             if r.prefill_capable()]
        by_id = {r.id: r for r in eligible}
        # Holders in preference order (primary first): the FIRST
        # surviving, unsaturated one wins — so a failover replay
        # (primary excluded/dead) lands on a secondary holder of the
        # request's prefix instead of a cold least-outstanding pick,
        # and the replay's re-prefill cost drops for free.
        for aff in self._affinity_holders(prompt):
            r = by_id.get(aff)
            if r is not None and r.outstanding \
                    < self.affinity_max_outstanding:
                return r, "affinity"
        if eligible:
            self._rr += 1
            return min(
                eligible,
                key=lambda r: (r.outstanding,
                               (self.replicas.index(r) + self._rr)
                               % len(self.replicas))), \
                "least_outstanding"
        # No closed replica in rotation: offer a HALF_OPEN one its
        # single live probe (exactly one concurrent claimant passes —
        # recovery.CircuitBreaker.try_probe).
        for r in half_open:
            if r.breaker.try_probe():
                return r, "half_open_probe"
        return None, "none"

    def _pick_prefill_tier(self) -> Optional[Replica]:
        """Least-outstanding DEDICATED prefill replica in rotation,
        or None.  The two-stage disagg schedule only activates when
        the fleet actually runs a prefill tier — a 'both' replica
        prefills fine, but bouncing a prompt through one buys no
        decode-lock relief, just an extra hop."""
        tier = [r for r in self.replicas
                if r.role == "prefill" and r.eligible()]
        if not tier:
            return None
        return min(tier, key=lambda r: r.outstanding)

    # -- fleet chaos -----------------------------------------------------

    def _poll_fleet_faults(self) -> None:
        if self.fleet_faults is None:
            return
        for site in FLEET_SITES:
            fired = self.fleet_faults.poll(site)
            if fired is None:
                continue
            idx = fired["replica"] % len(self.replicas)
            replica = self.replicas[idx]
            applied = False
            if site == "replica_kill":
                applied = replica.chaos_kill()
            elif site == "replica_hang":
                applied = replica.chaos_hang()
            elif site == "replica_slow":
                applied = replica.chaos_slow(fired["delay_s"])
            with self._stats_lock:
                key = site if applied else site + "_unsupported"
                self.fleet_faults_applied[key] = \
                    self.fleet_faults_applied.get(key, 0) + 1

    # -- the hedge watermark ---------------------------------------------

    def _observe_latency(self, dt: float) -> None:
        with self._lat_lock:
            self._lat_recent.append(dt)

    def _hedge_after_s(self) -> Optional[float]:
        if self.hedge == "off":
            return None
        if self.hedge != "p99":
            return max(self.hedge_min_s, float(self.hedge))
        with self._lat_lock:
            xs = sorted(self._lat_recent)
        if len(xs) < 8:
            # Too little signal for a p99: hedge only past the floor.
            return self.hedge_min_s if xs else None
        idx = min(len(xs) - 1, int(0.99 * (len(xs) - 1) + 0.9999))
        return max(self.hedge_min_s, xs[idx])

    # -- routing ---------------------------------------------------------

    def _forward_headers(self, replica: Replica,
                         rid: str) -> Dict[str, str]:
        """X-Request-Id forwarded REPLICA-WARD with the replica-id
        prefix (serving/debug.py's convention): the replica's access
        log, trace ring, and /requests/<id> all key on
        ``r0-<rid>`` — one grep string per (request, replica) leg of
        a failover."""
        fwd = format_replica_rid(replica.id, rid)
        return {"Content-Type": "application/json",
                "X-Request-Id": fwd}

    def _race(self, primary: _Attempt, deadline: float,
              payload_bytes: bytes, rid: str, prompt,
              exclude: set, note=None
              ) -> Tuple[_Attempt, Optional[_Attempt]]:
        """Wait the primary out, optionally firing ONE hedge at the
        watermark; returns (winner, loser).  The winner is the first
        attempt to reach a decisive outcome (ok/terminal); a
        retryable loser is just evidence, and a still-running loser
        is CANCELLED (connection close -> replica-side cancel).
        ``note(name, t, **args)`` (optional) receives the hedge
        lifecycle instants for the request-span record."""
        if note is None:
            def note(name, t, **args):
                pass
        hedge_after = self._hedge_after_s()
        hedge: Optional[_Attempt] = None
        t0 = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= deadline:
                # The caller maps this to a retryable timeout on the
                # primary; cancel everything in flight.
                primary.cancel()
                if hedge is not None:
                    hedge.cancel()
                    note("hedge_cancelled", time.monotonic(),
                         replica=hedge.replica.id,
                         reason="deadline")
                    with self._stats_lock:
                        self.hedges_cancelled_total += 1
                return primary, hedge
            if primary.done.is_set() and (
                    hedge is None or hedge.done.is_set()
                    or primary.outcome() != "retryable"):
                # Primary decided (or both are done).
                if hedge is not None and not hedge.done.is_set():
                    hedge.cancel()
                    note("hedge_cancelled", time.monotonic(),
                         replica=hedge.replica.id,
                         reason="primary_won")
                    with self._stats_lock:
                        self.hedges_cancelled_total += 1
                return primary, hedge
            if hedge is not None and hedge.done.is_set() \
                    and hedge.outcome() != "retryable":
                # The hedge won: cancel the straggling primary (the
                # PR 6 cancel path reclaims its slot).
                primary_live = not primary.done.is_set()
                if primary_live:
                    primary.cancel()
                note("hedge_won", time.monotonic(),
                     replica=hedge.replica.id,
                     **({"cancelled_primary": primary.replica.id}
                        if primary_live else {}))
                with self._stats_lock:
                    self.hedges_won_total += 1
                    if primary_live:
                        self.hedges_cancelled_total += 1
                return hedge, primary
            if hedge is None and hedge_after is not None \
                    and now - t0 >= hedge_after \
                    and not primary.done.is_set():
                second, _why = self._pick(
                    prompt, exclude | {primary.replica.id},
                    want="decode")
                if second is not None and self.budget.try_spend():
                    hedge = _Attempt(
                        second, "POST", "/generate", payload_bytes,
                        self._forward_headers(second, rid),
                        min(self.request_timeout_s,
                            max(0.05, deadline - now))).start()
                    note("hedge_fired", time.monotonic(),
                         replica=second.id,
                         watermark_s=round(hedge_after, 4))
                    with self._stats_lock:
                        self.hedges_fired_total += 1
                else:
                    if second is not None:
                        # A hedge target existed but the budget said
                        # no — the denial is part of the causal story
                        # (budget.denied_total already counted it).
                        note("retry_budget_denied", time.monotonic(),
                             for_="hedge")
                    hedge_after = None      # nothing to hedge onto
            # BLOCK, don't poll: before a hedge exists the only
            # wake-up sources are the primary finishing, the hedge
            # watermark, and the deadline — wait on the primary's
            # event up to the nearest of them.  Once a hedge is in
            # flight there are two events to watch, so a short
            # bounded wait keeps the race responsive (the hedge
            # window is the rare tail case, not the steady state).
            if hedge is None:
                wake = deadline
                if hedge_after is not None:
                    wake = min(wake, t0 + hedge_after)
                primary.done.wait(
                    max(0.001, wake - time.monotonic()))
            elif primary.done.is_set():
                # Primary already decided (retryable, or we'd have
                # returned): the hedge is the only pending event.
                hedge.done.wait(
                    max(0.001, deadline - time.monotonic()))
            else:
                primary.done.wait(0.005)

    def route_generate(self, req: Dict[str, Any],
                       rid: Optional[str] = None
                       ) -> Tuple[int, Dict[str, Any]]:
        """Route one /generate body; returns (status, response).
        Failure handling lives HERE, not in the client: failover with
        resume replay, bounded by the retry budget and
        ``max_attempts``, hedged past the p99 watermark.  The whole
        causal story — route decisions, attempt send/receive
        brackets, failovers, hedges, budget denials — lands in ONE
        terminal record in the router's history ring, the router half
        of ``GET /fleet/requests/<id>``."""
        rid = rid or new_request_id()
        t0 = time.monotonic()
        # Request-span trace: (name, t_start, t_end, args) tuples in
        # the router's monotonic clock, rendered into the record via
        # the same events_to_dicts the replica records use.
        trace: List[Tuple[str, float, float, Dict[str, Any]]] = []
        attempts_log: List[Dict[str, Any]] = []
        # With a TTFT objective armed the router needs the replica's
        # admission-anchored TTFT, so it injects a timings request
        # into the forwarded payload — and strips the block back off
        # the response when the CLIENT never asked for it.
        # Availability/latency objectives need no replica timings
        # (latency is the router's own clock), so they don't tax the
        # replicas with per-stream span rendering.
        slo_inject = self.slo is not None \
            and any(o.get("metric") == "ttft"
                    for o in self.slo.objectives.values()) \
            and not req.get("timings", False)
        partial: List[int] = []        # tokens recovered so far —
        #                                replayed with resume_tokens
        #                                (populated by the streaming
        #                                protocol, ROADMAP item 1;
        #                                empty replays are full
        #                                replays, same contract)

        def note(name, a, b=None, **args):
            trace.append((name, a, a if b is None else b, args))

        def log_attempt(att: _Attempt, *, hedge: bool,
                        resume_n: int) -> None:
            rec = _attempt_record(att, len(attempts_log) + 1, t0,
                                  hedge=hedge, resume_n=resume_n)
            attempts_log.append(rec)
            if att.t_send is not None:
                note("attempt", att.t_send,
                     att.t_recv if att.t_recv is not None
                     else time.monotonic(),
                     replica=att.replica.id, n=rec["n"],
                     outcome=rec["outcome"],
                     **({"code": att.code} if att.code is not None
                        else {}),
                     **({"hedge": True} if hedge else {}))

        def finish(code: int, resp: Dict[str, Any],
                   winner: Optional[_Attempt] = None
                   ) -> Tuple[int, Dict[str, Any]]:
            """Every terminal path funnels through here: the SLO
            observation and the history record are built from the
            same trace the response rode."""
            now = time.monotonic()
            if self.slo is not None:
                ttft_s = None
                if code == 200:
                    tm = ((resp or {}).get("timings") or {}) \
                        .get("ttft_ms")
                    if tm is not None and winner is not None \
                            and winner.t_send is not None:
                        # Client-visible TTFT: router queue/hedge
                        # time up to the WINNING send, plus the
                        # replica's admission-anchored TTFT.
                        ttft_s = (winner.t_send - t0) + tm / 1e3
                    else:
                        ttft_s = now - t0
                self.slo.observe(code, ttft_s=ttft_s,
                                 latency_s=now - t0)
            if slo_inject and isinstance(resp, dict):
                resp.pop("timings", None)
            # Router-side phase ledger (serving/forensics.py): the
            # same trace the record's timeline renders from, so the
            # two views of one request cannot disagree.
            ledger = None
            if self.forensics is not None or self.history.enabled:
                ledger = compute_router_ledger(trace, t0, now)
                if self.forensics is not None:
                    self.forensics.note(ledger, rid)
            if self.history.enabled:
                status = _terminal_status(code)
                replicas_involved: List[str] = []
                for a in attempts_log:
                    if a["replica"] not in replicas_involved:
                        replicas_involved.append(a["replica"])
                rec: Dict[str, Any] = {
                    "request_id": rid,
                    "t": round(time.time(), 3),
                    "path": "/generate",
                    "status": status,
                    "code": code,
                    "wall_s": round(now - t0, 6),
                    "attempts": attempts_log,
                    "replicas": replicas_involved,
                    "resume_tokens": len(partial),
                    "timeline": events_to_dicts(trace, t0),
                    **({"phases": ledger}
                       if ledger is not None else {}),
                }
                if isinstance(resp, dict):
                    if resp.get("reason"):
                        rec["reason"] = resp["reason"]
                    if status != "complete" and resp.get("error"):
                        rec["error"] = str(resp["error"])[:300]
                # "hedged" means a hedge FIRED for this request (the
                # attempt table's truth), not that it won — the
                # response's router.hedged only marks wins, and a
                # record whose summary disagreed with its own
                # attempt table would be poison during an incident.
                if any(a.get("hedge") for a in attempts_log):
                    rec["hedged"] = True
                self.history.record(rec)
            return code, resp

        if self.draining:
            with self._stats_lock:
                self.shed_total += 1
            return finish(503, {"error": "router is draining",
                                "reason": "draining",
                                "request_id": rid})
        self._poll_fleet_faults()
        with self._stats_lock:
            self.requests_total += 1
        self.budget.on_request()
        prompt = None
        rows = req.get("prompt")
        if isinstance(rows, list) and rows:
            prompt = rows[0] if isinstance(rows[0], list) else rows
        deadline_ms = req.get("deadline_ms")
        deadline = t0 + (min(self.request_timeout_s,
                             deadline_ms / 1e3)
                         if isinstance(deadline_ms, (int, float))
                         and not isinstance(deadline_ms, bool)
                         and deadline_ms > 0
                         else self.request_timeout_s)
        # Disaggregated two-stage schedule (docs/SERVING.md
        # "Disaggregated serving"): with a dedicated prefill tier in
        # rotation and a prompt long enough to amortize the handoff,
        # run STAGE 1 — prompt prefill on a prefill replica — before
        # the decode attempt loop.  Success records the prefill
        # replica as the prefix's PRIMARY holder, so the decode
        # replica the loop picks gets a fetch hint naming it and
        # ADMITS the prefill's KV over the wire lane (the kv_handoff)
        # instead of re-prefilling under its own decode lock.  A
        # prompt whose prefix already sits warm on a routable decode
        # replica skips stage 1 — affinity routing lands it there
        # with zero prefill work anywhere.  EVERY stage-1 failure
        # (dead prefill tier, timeout) degrades to decode-side
        # re-prefill: counted, never a request failure.
        disagg: Optional[Replica] = None
        if prompt and len(prompt) >= self.disagg_min_tokens \
                and not req.get("resume_tokens") \
                and all(isinstance(t, int) for t in prompt):
            pre = self._pick_prefill_tier()
            if pre is not None:
                by_id = {r.id: r for r in self.replicas}
                warm_decode = any(
                    h is not None and h.eligible()
                    and h.decode_capable()
                    and h.outstanding < self.affinity_max_outstanding
                    for h in (by_id.get(hid) for hid
                              in self._affinity_holders(prompt)))
                if not warm_decode:
                    disagg = pre
                    tp0 = time.monotonic()
                    p_att = _Attempt(
                        pre, "POST", "/prefill",
                        json.dumps({"prompt": list(prompt)}).encode(),
                        self._forward_headers(pre, rid),
                        min(self.request_timeout_s,
                            max(0.05, deadline - tp0))).start()
                    p_att.done.wait(max(0.05, deadline - tp0) + 1.0)
                    ok = p_att.done.is_set() \
                        and p_att.outcome() == "ok"
                    note("prefill_remote", tp0, time.monotonic(),
                         replica=pre.id,
                         tokens=len(prompt),
                         **({} if ok else {"failed": True}))
                    with self._stats_lock:
                        self.disagg_prefills_total += 1
                        if not ok:
                            self.disagg_prefill_failed_total += 1
                    if ok:
                        pre.note_success()
                        self._note_prefix(tuple(prompt), pre.id)
                    else:
                        if p_att.error is not None \
                                and not p_att.cancelled:
                            pre.note_failure()
                        disagg = None   # hint-less: re-prefill
        exclude: set = set()
        attempt_n = 0
        while True:
            payload = dict(req)
            if slo_inject:
                payload["timings"] = True
            if partial:
                # CROSS-REPLICA RESUME: prompt ++ received tokens,
                # RNG continues at position key len(partial)
                # (docs/DESIGN.md; token-identical per seed).
                payload["prompt"] = list(prompt) + partial
                payload["resume_tokens"] = len(partial)
            replica, why = self._pick(prompt, exclude,
                                      want="decode")
            if replica is None and exclude:
                # Every replica already failed this request once:
                # widen back out rather than shedding while capacity
                # exists (the failed one may have merely been busy).
                note("exclusions_widened", time.monotonic(),
                     excluded=sorted(exclude))
                exclude = set()
                replica, why = self._pick(prompt, exclude,
                                          want="decode")
            if replica is None:
                with self._stats_lock:
                    self.shed_total += 1
                    self.errors_total += 1
                return finish(503, {
                    "error": "no replica in rotation",
                    "reason": "no_replica", "request_id": rid,
                    "router": self._route_info(None, attempt_n,
                                               partial)})
            attempt_n += 1
            hint_holder: Optional[Replica] = None
            if why != "affinity":
                # Routed AWAY from the prefix's holders (saturation,
                # exclusion, drain, role split): hand the chosen
                # replica a FETCH HINT naming a live holder, so its
                # local miss can become a wire fetch instead of a
                # re-prefill.  A DRAINING holder still qualifies —
                # the drain window is exactly when its entries need
                # serving out.  The hint carries the holder link's
                # MEASURED wire_bytes_per_s / rtt_s (EWMA) when they
                # exist, so the fetcher's cost gate runs on observed
                # truth instead of PrefixFetchPolicy's static
                # defaults.
                holders = self._affinity_holders(prompt)
                if holders and replica.id not in holders:
                    by_id = {r.id: r for r in self.replicas}
                    for h in holders:
                        hr = by_id.get(h)
                        if hr is not None and (
                                hr.health_ok
                                or hr.health_reason == "draining"):
                            payload["prefix_hint"] = {
                                "host": hr.host, "port": hr.port,
                                "replica": hr.id,
                                **hr.link_estimates()}
                            hint_holder = hr
                            with self._stats_lock:
                                self.kv_fleet_hints_injected_total \
                                    += 1
                            note("prefix_hint", time.monotonic(),
                                 holder=hr.id)
                            break
            body = json.dumps(payload).encode()
            note("route", time.monotonic(), replica=replica.id,
                 why=why,
                 **({"excluded": sorted(exclude)} if exclude
                    else {}))
            att = _Attempt(
                replica, "POST", "/generate", body,
                self._forward_headers(replica, rid),
                min(self.request_timeout_s,
                    max(0.05, deadline - time.monotonic()))).start()
            winner, loser = self._race(att, deadline, body, rid,
                                       prompt, exclude, note=note)
            hedge_att = winner if winner is not att else loser
            log_attempt(att, hedge=False, resume_n=len(partial))
            if hedge_att is not None:
                log_attempt(hedge_att, hedge=True,
                            resume_n=len(partial))
            out = winner.outcome() if winner.done.is_set() \
                else "retryable"
            if out == "ok":
                winner.replica.note_success()
                resp = dict(winner.resp or {})
                # Recover the tokens generated by THIS attempt so a
                # later consumer (and the stats) see the stitched
                # stream; the replica already returned the FULL
                # sequence (resume replays carry the original budget).
                if partial:
                    with self._stats_lock:
                        self.resumes_total += 1
                        self.resumed_tokens_total += len(partial)
                # Holder learning: the response says where the
                # prefill actually came from.  A wire fetch (or a
                # hit on a replica the map didn't list) means the
                # winner now holds a copy — record it as a SECONDARY
                # holder so the next miss/failover can use it.
                src = resp.get("prefix_source")
                if src == "wire_fetch":
                    with self._stats_lock:
                        self.kv_fleet_wire_fetches_total += 1
                        if disagg is not None:
                            self.disagg_handoffs_total += 1
                    # The replica reports the fetch's measured bytes
                    # and wall — fold them into the HOLDER link's
                    # EWMA (the transfer ran holder -> winner), and
                    # stitch the ``kv_handoff`` span into the
                    # per-request timeline so the handoff cost is
                    # attributed, not guessed.  The span anchors at
                    # the winning attempt's send: the fetch runs at
                    # admission, causally inside the send/receive
                    # bracket.
                    fb = resp.get("prefix_fetch_bytes")
                    fs = resp.get("prefix_fetch_s")
                    if isinstance(fb, int) and fb > 0 \
                            and isinstance(fs, (int, float)) \
                            and not isinstance(fs, bool) and fs > 0:
                        if hint_holder is not None:
                            hint_holder.note_link_sample(
                                fb, float(fs))
                        if winner.t_send is not None:
                            note("kv_handoff", winner.t_send,
                                 winner.t_send + float(fs),
                                 bytes=fb,
                                 **({"holder": hint_holder.id}
                                    if hint_holder is not None
                                    else {}))
                hit_len = resp.get("prefix_hit_len")
                if src in ("wire_fetch", "local_hot",
                           "local_spilled") \
                        and isinstance(hit_len, int) \
                        and prompt and 0 < hit_len <= len(prompt) \
                        and all(isinstance(t, int)
                                for t in prompt[:hit_len]):
                    self._note_prefix(tuple(prompt[:hit_len]),
                                      winner.replica.id,
                                      primary=False)
                resp["request_id"] = rid
                resp["router"] = self._route_info(
                    winner.replica, attempt_n, partial,
                    hedged=(winner is not att))
                self._observe_latency(time.monotonic() - t0)
                with self._stats_lock:
                    self.completed_total += 1
                return finish(200, resp, winner)
            if out == "terminal":
                code = winner.code or 500
                resp = dict(winner.resp or {"error": "replica error"})
                resp["request_id"] = rid
                resp["router"] = self._route_info(
                    winner.replica, attempt_n, partial,
                    hedged=(winner is not att))
                with self._stats_lock:
                    self.errors_total += 1
                return finish(code, resp, winner)
            # Retryable: evidence against the replica, then fail
            # over within budget.  An attempt the ROUTER itself
            # cancelled (deadline expiry, hedge race) is NOT crash
            # evidence — its socket error is self-inflicted, and
            # counting it would let sustained short-deadline traffic
            # breaker-trip perfectly healthy replicas.
            for a in (att, loser):
                if a is not None and a.done.is_set() \
                        and a.outcome() == "retryable" \
                        and a.error is not None \
                        and not a.cancelled:
                    a.replica.note_failure()
                if a is not None:
                    exclude.add(a.replica.id)
            if time.monotonic() >= deadline:
                with self._stats_lock:
                    self.errors_total += 1
                return finish(504, {
                    "error": f"request deadline exhausted after "
                             f"{attempt_n} attempt(s)",
                    "reason": "deadline", "request_id": rid,
                    "router": self._route_info(replica, attempt_n,
                                               partial)})
            if attempt_n >= self.max_attempts:
                with self._stats_lock:
                    self.errors_total += 1
                    self.shed_total += 1
                return finish(503, {
                    "error": f"request failed on {attempt_n} "
                             f"replica(s); attempts exhausted",
                    "reason": "retries_exhausted", "request_id": rid,
                    "router": self._route_info(replica, attempt_n,
                                               partial)})
            if not self.budget.try_spend():
                # The sick-fleet contract: degrade to a FAST 503
                # instead of a retry storm.
                note("retry_budget_denied", time.monotonic(),
                     for_="failover")
                with self._stats_lock:
                    self.errors_total += 1
                    self.shed_total += 1
                return finish(503, {
                    "error": "retry budget exhausted (the fleet is "
                             "failing faster than live traffic "
                             "refills retries)",
                    "reason": "retry_budget", "request_id": rid,
                    "router": self._route_info(replica, attempt_n,
                                               partial)})
            with self._stats_lock:
                self.failovers_total += 1
            note("failover", time.monotonic(),
                 from_replica=replica.id,
                 resume_tokens=len(partial))
            # Jittered backoff (shared RetryPolicy), bounded by the
            # deadline.
            delay = min(self.retry_policy.delay_s(attempt_n - 1),
                        max(0.0, deadline - time.monotonic()))
            if delay > 0:
                time.sleep(delay)

    def _route_info(self, replica: Optional[Replica], attempts: int,
                    partial: List[int], *,
                    hedged: bool = False) -> Dict[str, Any]:
        return {
            **({"replica": replica.id} if replica is not None
               else {}),
            "attempts": attempts,
            **({"hedged": True} if hedged else {}),
            **({"resumed_tokens": len(partial)} if partial else {}),
        }

    def route_prefill(self, req: Dict[str, Any],
                      rid: Optional[str] = None
                      ) -> Tuple[int, Dict[str, Any]]:
        """Forward /prefill to the affinity replica (a growing
        session re-registers where its ancestor lives) or the least-
        outstanding one, and record the prefix -> replica binding the
        affinity router consults."""
        rid = rid or new_request_id()
        t0 = time.monotonic()

        def finish(code: int, resp: Dict[str, Any],
                   att: Optional[_Attempt] = None, why: str = ""
                   ) -> Tuple[int, Dict[str, Any]]:
            if self.history.enabled:
                attempts = []
                if att is not None:
                    attempts.append(_attempt_record(att, 1, t0))
                self.history.record({
                    "request_id": rid,
                    "t": round(time.time(), 3),
                    "path": "/prefill",
                    "status": _terminal_status(code),
                    "code": code,
                    "wall_s": round(time.monotonic() - t0, 6),
                    "attempts": attempts,
                    "replicas": [a["replica"] for a in attempts],
                    **({"why": why} if why else {}),
                    **({"reason": resp.get("reason")}
                       if isinstance(resp, dict)
                       and resp.get("reason") else {}),
                })
            return code, resp

        if self.draining:
            with self._stats_lock:
                self.shed_total += 1
            return finish(503, {"error": "router is draining",
                                "reason": "draining",
                                "request_id": rid})
        prompt = None
        rows = req.get("prompt")
        if isinstance(rows, list) and rows:
            prompt = rows[0] if isinstance(rows[0], list) else rows
        replica, why = self._pick(prompt, set(), want="prefill")
        if replica is None:
            with self._stats_lock:
                self.shed_total += 1
            return finish(503, {"error": "no replica in rotation",
                                "reason": "no_replica",
                                "request_id": rid})
        att = _Attempt(replica, "POST", "/prefill",
                       json.dumps(req).encode(),
                       self._forward_headers(replica, rid),
                       self.request_timeout_s).start()
        att.done.wait(self.request_timeout_s + 1.0)
        if att.outcome() == "ok" and prompt \
                and all(isinstance(t, int) for t in prompt):
            self._note_prefix(tuple(prompt), replica.id)
            resp = dict(att.resp or {})
            resp["request_id"] = rid
            resp["router"] = {"replica": replica.id}
            return finish(200, resp, att, why)
        if att.error is not None:
            replica.note_failure()
            with self._stats_lock:
                self.errors_total += 1
            return finish(503, {"error": f"replica {replica.id} "
                                         f"failed: "
                                         f"{type(att.error).__name__}",
                                "reason": "replica_unreachable",
                                "request_id": rid}, att, why)
        resp = dict(att.resp or {"error": "replica error"})
        resp["request_id"] = rid
        return finish(att.code or 500, resp, att, why)

    # -- fleet observability: cross-tier stitching -----------------------

    def fleet_request(self, rid: str) -> Optional[Dict[str, Any]]:
        """``GET /fleet/requests/<id>``: ONE merged causal timeline
        for a routed request — the router's record (route decisions,
        attempt brackets, failovers, hedges) stitched with every
        involved replica's own ``GET /requests/<rN-id>`` record.

        CLOCK RECONCILIATION: the router and each replica run
        independent monotonic clocks, so replica-local offsets are
        meaningless fleet-wide.  Each replica segment is anchored at
        the router's SEND timestamp for that attempt and clamped
        inside the send/receive bracket — by causality the replica
        processed the request inside that bracket, so the stitched
        ordering is consistent even with arbitrary clock skew (the
        residual error is the one-way network delay, bounded by the
        bracket width; events the clamp had to move carry
        ``clamped: true``).  A re-attempt on the SAME replica shares
        one replica-side record (replace-by-id retention): only the
        LAST attempt's segment carries it, earlier ones read
        ``record_superseded``."""
        rec = self.history.get(rid)
        if rec is None:
            return None
        by_id = {r.id: r for r in self.replicas}
        merged: List[Dict[str, Any]] = []
        for ev in rec.get("timeline", []):
            merged.append({"at_ms": ev.get("start_ms"),
                           **({"dur_ms": ev["dur_ms"]}
                              if ev.get("dur_ms") else {}),
                           "source": "router",
                           "event": ev.get("name"),
                           **({"args": ev["args"]}
                              if ev.get("args") else {})})
        attempts = rec.get("attempts", [])
        # One replica record per replica (replace-by-id retention):
        # fetch it for the LAST attempt on each replica only — and
        # fetch the replicas CONCURRENTLY, like the federation
        # scrape: a failover across hung replicas must not make the
        # endpoint that debugs it pay each timeout back to back.
        last_per_replica = {a["replica"]: a["n"] for a in attempts}
        fetches: Dict[str, List] = {}
        fetch_threads = []
        for replica_id in last_per_replica:
            replica = by_id.get(replica_id)
            if replica is None:
                continue
            fwd = format_replica_rid(replica_id, rid)
            slot: List = [None, {}]
            fetches[replica_id] = slot

            def fetch(replica=replica, fwd=fwd, slot=slot):
                slot[0], slot[1] = self._http_json(
                    replica, "GET", f"/requests/{fwd}")

            t = threading.Thread(target=fetch, daemon=True,
                                 name=f"fleet-stitch-{replica_id}")
            fetch_threads.append(t)
            t.start()
        for t in fetch_threads:
            t.join(timeout=self.probe_timeout_s + 1.0)
        segments: List[Dict[str, Any]] = []
        for att in attempts:
            replica_id = att["replica"]
            seg: Dict[str, Any] = {
                "replica": replica_id,
                "attempt": att["n"],
                "request_id": format_replica_rid(replica_id, rid),
                "send_ms": att.get("send_ms"),
                "recv_ms": att.get("recv_ms"),
            }
            # Clock-skew annotation: the anchor correction below is
            # applied silently; surfacing the replica's ESTIMATED
            # skew (probe-derived, host clocks) — and flagging it
            # past the suspect threshold — stops a bad clock from
            # hiding behind a plausible-looking causal order.
            rep_obj = by_id.get(replica_id)
            if rep_obj is not None \
                    and rep_obj.clock_skew_s is not None:
                seg["clock_skew_est_s"] = round(
                    rep_obj.clock_skew_s, 6)
                # Explicit False = "checked, inside the threshold";
                # an absent key would be ambiguous with "no probe
                # data yet".
                seg["clock_skew_suspect"] = \
                    abs(rep_obj.clock_skew_s) \
                    > self.clock_skew_suspect_s
            if last_per_replica.get(replica_id) != att["n"]:
                # An earlier attempt on a replica a later attempt
                # also hit: the replica's ring keeps only the latest
                # record for this ID.
                seg["record_superseded"] = True
                segments.append(seg)
                continue
            if replica_id not in fetches:
                seg["fetch_error"] = "replica_gone"
                segments.append(seg)
                continue
            status, body = fetches[replica_id]
            if status is None:
                seg["fetch_error"] = "unreachable"
                segments.append(seg)
                continue
            if status != 200:
                seg["fetch_error"] = f"http_{status}"
                if isinstance(body, dict) and body.get("error"):
                    seg["fetch_detail"] = str(body["error"])[:200]
                segments.append(seg)
                continue
            seg["record"] = body
            # Lift the replica-computed phase ledger onto the
            # segment VERBATIM — the per-attempt decomposition of
            # the stitched timeline is the same bytes the replica's
            # history record carries (the no-drift pin: one
            # computation, serving/forensics.py).
            if isinstance(body.get("phases"), dict):
                seg["phases"] = body["phases"]
            seg["clamped_events"] = self._anchor_segment(
                seg, body, merged)
            segments.append(seg)
        merged.sort(key=lambda e: (e.get("at_ms")
                                   if e.get("at_ms") is not None
                                   else 0.0))
        return {
            "request_id": rid,
            "status": rec.get("status"),
            "path": rec.get("path"),
            "wall_s": rec.get("wall_s"),
            "replicas": rec.get("replicas", []),
            "router": rec,
            "segments": segments,
            "timeline": merged,
        }

    @staticmethod
    def _anchor_segment(seg: Dict[str, Any],
                        record: Dict[str, Any],
                        merged: List[Dict[str, Any]]) -> int:
        """Fold one replica record's stream timelines into the
        merged fleet timeline, anchored to the attempt's send/receive
        bracket.  Returns how many events the clamp had to move."""
        send_ms = seg.get("send_ms")
        recv_ms = seg.get("recv_ms")
        if send_ms is None:
            return 0
        clamped = 0
        for stream in record.get("streams", []):
            for ev in stream.get("timeline", []):
                at = send_ms + max(0.0, ev.get("start_ms", 0.0))
                dur = ev.get("dur_ms", 0.0) or 0.0
                was_clamped = False
                if recv_ms is not None:
                    if at > recv_ms:
                        at, was_clamped = recv_ms, True
                    if at + dur > recv_ms:
                        dur, was_clamped = recv_ms - at, True
                if was_clamped:
                    clamped += 1
                merged.append({
                    "at_ms": round(at, 3),
                    **({"dur_ms": round(dur, 3)} if dur else {}),
                    "source": seg["replica"],
                    "event": ev.get("name"),
                    **({"args": ev["args"]} if ev.get("args")
                       else {}),
                    **({"clamped": True} if was_clamped else {}),
                })
        return clamped

    # -- fleet observability: metrics federation -------------------------

    # Families whose fleet rollup is a plain sum (counters and the
    # cumulative histogram/summary component series); gauges get
    # sum AND min/max (a fleet-wide queue_len sum says load, the max
    # says imbalance).
    _SUM_TYPES = frozenset({"counter", "histogram", "summary"})

    def fleet_metrics_text(self) -> str:
        """``GET /fleet/metrics``: the router's own exposition, every
        replica's ``/metrics`` re-exported with a ``replica=`` label,
        and fleet ROLLUPS (``<name>_fleet{agg=...}``) — one scrape
        target for the whole tier.  A replica that fails its scrape
        is reported via ``ptpu_fleet_replica_scrape_ok{replica=}``
        and the ``fleet_scrape_errors_total`` counter; its series are
        simply absent (partial federation beats a 500)."""
        errors = 0
        fam_types: Dict[str, str] = {}
        fam_lines: "OrderedDict[str, List[str]]" = OrderedDict()
        rollup: "OrderedDict[Tuple[str, str], List[float]]" = \
            OrderedDict()
        scrape_ok: List[Tuple[str, int]] = []
        replicas = list(self.replicas)
        # Scrape the fleet CONCURRENTLY: a sequential walk pays each
        # hung replica's full timeout back to back (N x timeout on
        # the scrape path, exactly when the fleet is degraded and a
        # scraper's own timeout is ticking); the fetches are
        # independent, so one thread each, joined within the bounded
        # socket timeout they all share.
        results: List[Optional[Tuple[Optional[int], bytes]]] = \
            [None] * len(replicas)

        def scrape(i: int, replica: Replica) -> None:
            results[i] = self._http_text(replica, "GET", "/metrics")

        threads = [threading.Thread(target=scrape, args=(i, r),
                                    daemon=True,
                                    name=f"fleet-scrape-{r.id}")
                   for i, r in enumerate(replicas)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 1.0)
        for replica, res in zip(replicas, results):
            status, raw = res if res is not None else (None, b"")
            if status != 200 or not raw:
                errors += 1
                scrape_ok.append((replica.id, 0))
                continue
            try:
                types, samples = parse_prometheus_families(
                    raw.decode("utf-8", "replace"))
            except ValueError:
                errors += 1
                scrape_ok.append((replica.id, 0))
                continue
            scrape_ok.append((replica.id, 1))
            for name, t in types.items():
                fam_types.setdefault(name, t)
            for name, labels, value in samples:
                lab = f'replica="{replica.id}"' \
                    + (f",{labels}" if labels else "")
                fam_lines.setdefault(name, []).append(
                    f"{name}{{{lab}}} {value}")
                try:
                    rollup.setdefault((name, labels),
                                      []).append(float(value))
                except ValueError:
                    pass
        with self._stats_lock:
            self.fleet_scrapes_total += 1
            self.fleet_scrape_errors_total += errors
        # Router's own metrics AFTER the counters above so the scrape
        # that failed is already visible in the exposition it emits.
        lines = [self.metrics_text().rstrip("\n")]
        lines.append("# TYPE ptpu_fleet_replica_scrape_ok gauge")
        for rid_, ok in scrape_ok:
            lines.append(
                f'ptpu_fleet_replica_scrape_ok{{replica="{rid_}"}} '
                f'{ok}')
        for name, ls in fam_lines.items():
            t = self._family_type(name, fam_types)
            if t:
                lines.append(f"# TYPE {name} {t}")
            lines.extend(ls)
        # Fleet rollups: per distinct (family, label-set), summed
        # across replicas — and min/max spread for gauges.
        emitted_type: set = set()
        for (name, labels), values in rollup.items():
            t = self._family_type(name, fam_types)
            if t in self._SUM_TYPES:
                aggs = (("sum", sum(values)),)
            elif t == "gauge":
                aggs = (("sum", sum(values)),
                        ("min", min(values)),
                        ("max", max(values)))
            else:
                continue            # untyped: no meaningful rollup
            rname = f"{name}_fleet"
            if rname not in emitted_type:
                emitted_type.add(rname)
                lines.append(
                    f"# TYPE {rname} "
                    f"{'counter' if t in self._SUM_TYPES else 'gauge'}")
            for agg, v in aggs:
                lab = f'agg="{agg}"' + (f",{labels}" if labels
                                        else "")
                v = round(v, 6)
                lines.append(f"{rname}{{{lab}}} "
                             f"{int(v) if v == int(v) else v}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _family_type(name: str, types: Dict[str, str]
                     ) -> Optional[str]:
        """The declared TYPE for a SAMPLE name: direct hit, or the
        histogram/summary component suffixes resolved to their
        family's declaration."""
        t = types.get(name)
        if t is not None:
            return t
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                return types.get(name[:-len(suffix)])
        return None

    def fleet_anomalies(self) -> Dict[str, Any]:
        """``GET /fleet/anomalies``: the router sentry's own findings
        (route pick / attempt / retry-backoff phases) merged with
        every replica's ``GET /anomalies``, ranked worst-first by
        score (observed share over baseline EWMA).  Each finding
        carries its ``source`` (``router`` or the replica id) and its
        exemplar request ids — replica exemplars resolve through
        ``GET /fleet/requests/<id>`` once prefixed back to the
        router-visible id.  A replica that fails the fetch is listed
        under ``fetch_errors`` and its findings are simply absent
        (partial forensics beats a 500, same contract as
        ``/fleet/metrics``)."""
        replicas = list(self.replicas)
        results: List[Optional[Tuple[Optional[int], Any]]] = \
            [None] * len(replicas)

        def fetch(i: int, replica: Replica) -> None:
            results[i] = self._http_json(replica, "GET", "/anomalies")

        threads = [threading.Thread(target=fetch, args=(i, r),
                                    daemon=True,
                                    name=f"fleet-anomalies-{r.id}")
                   for i, r in enumerate(replicas)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 1.0)
        findings: List[Dict[str, Any]] = []
        phase_share: Dict[str, Dict[str, float]] = {}
        fetch_errors: List[str] = []
        if self.forensics is not None:
            own = self.forensics.report()
            for f in own.get("findings", []):
                findings.append({"source": "router", **f})
            phase_share["router"] = own.get("phase_share", {})
        for replica, res in zip(replicas, results):
            status, body = res if res is not None else (None, None)
            if status != 200 or not isinstance(body, dict):
                fetch_errors.append(replica.id)
                continue
            for f in body.get("findings", []):
                findings.append({"source": replica.id, **f})
            share = body.get("phase_share")
            if isinstance(share, dict):
                phase_share[replica.id] = share
        findings.sort(key=lambda f: -float(f.get("score", 0.0)))
        return {"findings": findings,
                "phase_share": phase_share,
                "fetch_errors": fetch_errors,
                "replicas_polled": len(replicas)}

    # -- rolling restart -------------------------------------------------

    def fleet_restart(self) -> Dict[str, Any]:
        """``POST /fleet/restart``: drain-restart every replica, one
        at a time, never dropping the ready count below
        ``min_ready``.  Returns immediately; progress rides
        ``restart_state`` in stats()/info.  409 (RuntimeError) when
        one is already running; ValueError when the fleet has
        non-restartable replicas."""
        not_restartable = [r.id for r in self.replicas
                           if not r.restartable]
        if not_restartable:
            raise ValueError(
                f"replicas {not_restartable} are not restartable "
                f"from this router (URL replicas restart via their "
                f"orchestrator; drain them directly instead)")
        with self._restart_lock:
            if self.restart_state["in_progress"]:
                raise RuntimeError(
                    "a rolling restart is already in progress")
            self.restart_state = {
                "in_progress": True, "completed": 0,
                "rounds_total": len(self.replicas),
                "last_error": None,
                "min_ready_floor_observed": self._ready_count()}
        t = threading.Thread(target=self._rolling_restart_run,
                             daemon=True, name="fleet-restart")
        t.start()
        return dict(self.restart_state)

    def _ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.up())

    def _note_ready_floor(self) -> None:
        n = self._ready_count()
        with self._restart_lock:
            floor = self.restart_state.get(
                "min_ready_floor_observed")
            if floor is None or n < floor:
                self.restart_state["min_ready_floor_observed"] = n

    def _rolling_restart_run(self) -> None:
        err = None
        try:
            for replica in list(self.replicas):
                # Gate: taking this replica out must leave min_ready
                # in rotation.
                gate_deadline = time.monotonic() + 120.0
                while self._ready_count() - (1 if replica.up()
                                             else 0) < self.min_ready:
                    if time.monotonic() > gate_deadline:
                        raise RuntimeError(
                            f"fleet never reached min_ready="
                            f"{self.min_ready}+1 before restarting "
                            f"{replica.id}")
                    time.sleep(0.05)
                replica.draining = True     # out of rotation FIRST:
                #                             new requests route away
                self._note_ready_floor()
                self._drain_replica(replica)
                # Cache half of the drain: push the drainee's prefix
                # entries to a router-chosen successor BEFORE the
                # restart flushes them.  Best-effort by contract —
                # the restart proceeds whatever happens here.
                if self.prefix_handoff_enabled:
                    self._drain_handoff(replica)
                else:
                    # No migration: the restart flushes the store the
                    # drainee's affinity bindings point at.
                    self._affinity_replace(replica.id, None)
                replica.restart()
                self._await_healthy(replica)
                replica.draining = False
                replica.health_ok = True
                replica.health_reason = None
                replica.note_success()      # fresh breaker history
                self._note_ready_floor()
                with self._restart_lock:
                    self.restart_state["completed"] += 1
                    self.restarts_completed_total += 1
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        finally:
            with self._restart_lock:
                self.restart_state["in_progress"] = False
                self.restart_state["last_error"] = err

    def _drain_replica(self, replica: Replica,
                       timeout_s: float = 120.0) -> None:
        """POST /drain (idempotent) and poll the in-flight snapshot
        to zero — the drain-aware half of the rolling restart."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status, snap = self._http_json(replica, "POST",
                                           "/drain", body=b"")
            if status == 200 \
                    and snap.get("slots_active", 0) == 0 \
                    and snap.get("queue_len", 0) == 0:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"replica {replica.id} did not drain within "
            f"{timeout_s}s")

    def _drain_handoff(self, replica: Replica,
                       timeout_s: float = 30.0) -> None:
        """Ask a DRAINED replica to push its prefix entries to a
        successor (POST /prefix/handoff) and re-point the affinity
        map accordingly.  Every failure path is absorbed: a replica
        without the endpoint (no paged engine, older build) answers
        404 and the restart just proceeds with the seed behavior —
        a cold post-restart cache."""
        successor = None
        candidates = [r for r in self.replicas
                      if r.id != replica.id and r.eligible()]
        if candidates:
            successor = min(candidates,
                            key=lambda r: r.outstanding)
        if successor is None:
            # Nowhere to hand off (single-replica fleet, everyone
            # else down): the drainee's entries die with the
            # restart, so the affinity map must forget it.
            with self._stats_lock:
                self.kv_fleet_handoff_failed_total += 1
            self._affinity_replace(replica.id, None)
            return
        t0 = time.monotonic()
        status, raw = self._http_text(
            replica, "POST", "/prefix/handoff",
            body=json.dumps({"host": successor.host,
                             "port": successor.port}).encode(),
            timeout_s=timeout_s)
        wall_s = time.monotonic() - t0
        out: Dict[str, Any] = {}
        if status == 200:
            try:
                parsed = json.loads(raw)
                if isinstance(parsed, dict):
                    out = parsed
            except ValueError:
                pass
        sent = out.get("sent", 0) if status == 200 else 0
        # A completed handoff is a measured transfer FROM the
        # drainee: feed the link calibration EWMA (satellite of
        # ROADMAP item 3 — measurements over defaults).
        pushed = out.get("bytes")
        if status == 200 and isinstance(pushed, int) and pushed > 0:
            replica.note_link_sample(pushed, wall_s)
        with self._stats_lock:
            self.kv_fleet_handoffs_total += 1
            if isinstance(sent, int) and sent > 0:
                self.kv_fleet_handoff_entries_total += sent
            if status != 200:
                self.kv_fleet_handoff_failed_total += 1
        # Successful push: the successor now PRIMARILY holds what
        # the drainee held, so traffic (and fetch hints) follow the
        # entries.  Anything else: drop the drainee's bindings — its
        # restart flushes the store they pointed at.
        self._affinity_replace(
            replica.id,
            successor.id if isinstance(sent, int) and sent > 0
            else None)

    def fleet_prefix_rebalance(self) -> Dict[str, Any]:
        """``POST /fleet/prefix/rebalance``: the one-copy-somewhere
        eviction pass.  Scrape every up replica's ``GET
        /prefix/index`` (stable cross-replica entry keys), find
        prefixes with REDUNDANT host-tier copies, keep the
        most-useful copy — device-tier copies always win (they are a
        replica's live working set and never evicted by hint); among
        host-tier copies the highest hit count survives — and post
        the rest back as ``/prefix/evict`` hints.  Budget freed this
        way goes back to prefixes only one replica holds, which is
        what makes the fleet's aggregate host tier worth more than N
        private ones."""
        inventory: Dict[str, List[Tuple[Replica, Dict[str, Any]]]] \
            = {}
        scraped = []
        for r in self.replicas:
            if not r.up():
                continue
            status, parsed = self._http_json(r, "GET",
                                             "/prefix/index")
            if status != 200 or not isinstance(
                    parsed.get("entries"), list):
                continue
            scraped.append(r.id)
            for ent in parsed["entries"]:
                if isinstance(ent, dict) and \
                        isinstance(ent.get("key"), str):
                    inventory.setdefault(ent["key"], []).append(
                        (r, ent))
        evict: Dict[str, List[str]] = {}   # replica id -> keys
        by_id = {r.id: r for r in self.replicas}
        for key, copies in inventory.items():
            if len(copies) < 2:
                continue
            host_copies = [(r, e) for r, e in copies
                           if e.get("tier") == "host"]
            device_held = any(e.get("tier") == "device"
                              for _, e in copies)
            if not host_copies:
                continue
            if device_held:
                doomed = host_copies
            else:
                # Keep the host copy with the most hits (stable on
                # ties: first scraped) — evict the rest.
                keep = max(host_copies,
                           key=lambda re: re[1].get("hits", 0))
                doomed = [c for c in host_copies if c is not keep]
            for r, _ in doomed:
                evict.setdefault(r.id, []).append(key)
        hinted = 0
        evicted = 0
        for rid_, keys in evict.items():
            hinted += len(keys)
            status, parsed = self._http_json(
                by_id[rid_], "POST", "/prefix/evict",
                body=json.dumps({"keys": keys}).encode())
            if status == 200:
                got = parsed.get("evicted", 0)
                if isinstance(got, int):
                    evicted += got
        with self._stats_lock:
            self.kv_fleet_rebalances_total += 1
            self.kv_fleet_evict_hints_total += hinted
        return {"replicas_scraped": scraped,
                "prefixes_seen": len(inventory),
                "duplicates": sum(
                    1 for c in inventory.values() if len(c) > 1),
                "evict_hints": hinted,
                "evicted": evicted}

    def _rebalance_due(self) -> bool:
        """Cadence gate, read off the federated ``kv_host_*``
        gauges: a rebalance pass can only move host-tier bytes, so
        it runs only while at least TWO up replicas report host-tier
        entries (one holder can't have a redundant copy; zero
        holders have nothing to move).  Keeps the idle-fleet cadence
        at one cheap /info scrape per replica instead of a full
        /prefix/index inventory."""
        holders = 0
        for r in self.replicas:
            if not r.up():
                continue
            status, parsed = self._http_json(r, "GET", "/info")
            entries = parsed.get("kv_host_entries", 0) \
                if status == 200 else 0
            if isinstance(entries, int) and entries > 0:
                holders += 1
                if holders >= 2:
                    return True
        return False

    def _rebalance_loop(self) -> None:
        """The ``--rebalance-every`` cadence thread: drive the same
        one-copy-somewhere pass an operator POST triggers, on a
        timer.  ONE-FLIGHT: the cadence and operator triggers share
        a non-blocking lock, so a slow pass is skipped, never
        stacked; failures are logged and counted, never raised (a
        broken rebalance must not take the cadence thread — or the
        router — down with it)."""
        deadline = time.monotonic() + self.rebalance_every_s
        while not self._stop:
            if time.monotonic() < deadline:
                time.sleep(0.02)
                continue
            deadline = time.monotonic() + self.rebalance_every_s
            if not self._rebalance_flight.acquire(blocking=False):
                continue
            try:
                if not self._rebalance_due():
                    continue
                with self._stats_lock:
                    self.kv_fleet_rebalance_runs_total += 1
                self.fleet_prefix_rebalance()
            except Exception as e:
                with self._stats_lock:
                    self.kv_fleet_rebalance_failed_total += 1
                logger.warning("cadenced prefix rebalance failed: "
                               "%s: %s", type(e).__name__, e)
            finally:
                self._rebalance_flight.release()

    def _await_healthy(self, replica: Replica,
                       timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status, _ = self._http_json(replica, "GET", "/healthz")
            if status == 200:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"replica {replica.id} did not come back healthy "
            f"within {timeout_s}s of its restart")

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """ONE dict behind /metrics and /info (the no-drift contract
        every serving counter family follows) — held STRUCTURALLY by
        tests/test_fleet_observability.py: every key here must render
        on /metrics per STATS_METRIC_RENAMES/STATS_METRIC_EXEMPT."""
        with self._stats_lock:
            counters = {
                "requests_total": self.requests_total,
                "completed_total": self.completed_total,
                "errors_total": self.errors_total,
                "shed_total": self.shed_total,
                "failovers_total": self.failovers_total,
                "resumes_total": self.resumes_total,
                "resumed_tokens_total": self.resumed_tokens_total,
                "hedges_fired_total": self.hedges_fired_total,
                "hedges_won_total": self.hedges_won_total,
                "hedges_cancelled_total": self.hedges_cancelled_total,
                "fleet_scrapes_total": self.fleet_scrapes_total,
                "fleet_scrape_errors_total":
                    self.fleet_scrape_errors_total,
                "kv_fleet_hints_injected_total":
                    self.kv_fleet_hints_injected_total,
                "kv_fleet_wire_fetches_total":
                    self.kv_fleet_wire_fetches_total,
                "kv_fleet_handoffs_total":
                    self.kv_fleet_handoffs_total,
                "kv_fleet_handoff_entries_total":
                    self.kv_fleet_handoff_entries_total,
                "kv_fleet_handoff_failed_total":
                    self.kv_fleet_handoff_failed_total,
                "kv_fleet_rebalances_total":
                    self.kv_fleet_rebalances_total,
                "kv_fleet_evict_hints_total":
                    self.kv_fleet_evict_hints_total,
                "kv_fleet_rebalance_runs_total":
                    self.kv_fleet_rebalance_runs_total,
                "kv_fleet_rebalance_failed_total":
                    self.kv_fleet_rebalance_failed_total,
                "disagg_prefills_total":
                    self.disagg_prefills_total,
                "disagg_prefill_failed_total":
                    self.disagg_prefill_failed_total,
                "disagg_handoffs_total":
                    self.disagg_handoffs_total,
                "fleet_faults_applied":
                    dict(self.fleet_faults_applied),
            }
        with self._restart_lock:
            restart = dict(self.restart_state)
            restarts_total = self.restarts_completed_total
        with self._affinity_lock:
            affinity_entries = len(self._affinity)
        probe_counts, probe_sum, probe_n = \
            self.probe_hist.snapshot()
        return {
            **counters,
            **self.budget.stats(),
            "replicas": [r.describe() for r in self.replicas],
            "replicas_ready": self._ready_count(),
            "draining": self.draining,
            "hedge": self.hedge,
            "affinity_entries": affinity_entries,
            "rolling_restart": restart,
            "rolling_restarts_completed_total": restarts_total,
            # Router-side request spans: ring occupancy/evictions
            # (GET /fleet/requests) — serving/debug.RequestHistory.
            **self.history.stats(),
            # Per-probe wall-time histogram (per-bucket counts, the
            # render_histogram shape — same idiom as the engine's
            # spec-acceptance histogram).
            "probe_duration_buckets": list(self.probe_hist.buckets),
            "probe_duration_hist": probe_counts,
            "probe_duration_sum": round(probe_sum, 6),
            "probe_duration_count": probe_n,
            **({"slo": self.slo.stats()}
               if self.slo is not None else {}),
            **({"fleet_fault_stats": self.fleet_faults.stats()}
               if self.fleet_faults is not None else {}),
        }

    def metrics_text(self) -> str:
        """Prometheus text rendered FROM stats() — the same dict
        /info returns."""
        st = self.stats()
        lines = []

        def counter(name, value):
            lines.append(f"# TYPE ptpu_router_{name} counter")
            lines.append(f"ptpu_router_{name} {value}")

        def gauge(name, value):
            lines.append(f"# TYPE ptpu_router_{name} gauge")
            lines.append(f"ptpu_router_{name} {value}")

        for k in ("requests_total", "completed_total", "errors_total",
                  "shed_total", "failovers_total", "resumes_total",
                  "resumed_tokens_total", "hedges_fired_total",
                  "hedges_won_total", "hedges_cancelled_total",
                  "retry_budget_spent_total",
                  "retry_budget_denied_total",
                  "fleet_scrapes_total",
                  "fleet_scrape_errors_total",
                  "kv_fleet_hints_injected_total",
                  "kv_fleet_wire_fetches_total",
                  "kv_fleet_handoffs_total",
                  "kv_fleet_handoff_entries_total",
                  "kv_fleet_handoff_failed_total",
                  "kv_fleet_rebalances_total",
                  "kv_fleet_evict_hints_total",
                  "kv_fleet_rebalance_runs_total",
                  "kv_fleet_rebalance_failed_total",
                  "disagg_prefills_total",
                  "disagg_prefill_failed_total",
                  "disagg_handoffs_total",
                  "request_records_total"):
            counter(k, st[k])
        counter("request_records_evicted_total",
                st["request_records_evicted"])
        gauge("retry_budget_level", st["retry_budget_level"])
        gauge("retry_budget_ratio", st["retry_budget_ratio"])
        gauge("retry_budget_burst", st["retry_budget_burst"])
        gauge("replicas", len(st["replicas"]))
        gauge("replicas_ready", st["replicas_ready"])
        gauge("draining", int(st["draining"]))
        gauge("affinity_entries", st["affinity_entries"])
        gauge("request_history", st["request_history"])
        gauge("request_records", st["request_records"])
        gauge("rolling_restart_in_progress",
              int(st["rolling_restart"]["in_progress"]))
        counter("rolling_restarts_completed_total",
                st["rolling_restarts_completed_total"])
        # Per-probe wall-time histogram, rendered by the SAME shared
        # telemetry helper as every serving histogram (satellite: a
        # slow-but-alive replica shows up here before the hedge
        # watermark trips).
        lines += render_histogram(
            "ptpu_router_probe_duration_seconds",
            st["probe_duration_buckets"], st["probe_duration_hist"],
            st["probe_duration_sum"], st["probe_duration_count"])
        # SLO layer: burn-rate / target / violation families per
        # declared objective, from the same stats() dict.
        if "slo" in st:
            slo = st["slo"]
            objectives = sorted(slo["objectives"].items())
            lines.append("# TYPE ptpu_router_slo_burn_rate gauge")
            for name, o in objectives:
                lines.append(
                    f'ptpu_router_slo_burn_rate'
                    f'{{objective="{name}"}} {o["burn_rate"]}')
            lines.append("# TYPE ptpu_router_slo_target gauge")
            for name, o in objectives:
                lines.append(
                    f'ptpu_router_slo_target'
                    f'{{objective="{name}"}} {o["target"]}')
            lines.append(
                "# TYPE ptpu_router_slo_violations_total counter")
            for name, o in objectives:
                lines.append(
                    f'ptpu_router_slo_violations_total'
                    f'{{objective="{name}"}} '
                    f'{o["violations_total"]}')
            gauge("slo_window_observations",
                  slo["window_observations"])
        lines.append("# TYPE ptpu_router_replica_up gauge")
        for r in st["replicas"]:
            lines.append(
                f'ptpu_router_replica_up{{replica="{r["id"]}"}} '
                f'{int(r["up"])}')
        lines.append("# TYPE ptpu_router_replica_outstanding gauge")
        for r in st["replicas"]:
            lines.append(
                f'ptpu_router_replica_outstanding'
                f'{{replica="{r["id"]}"}} {r["outstanding"]}')
        lines.append(
            "# TYPE ptpu_router_replica_probe_failures gauge")
        for r in st["replicas"]:
            lines.append(
                f'ptpu_router_replica_probe_failures'
                f'{{replica="{r["id"]}"}} '
                f'{r["consecutive_probe_failures"]}')
        # Most recent probe wall per replica: the labeled twin of the
        # probe-duration histogram, so the SLOW replica is nameable.
        lines.append(
            "# TYPE ptpu_router_replica_last_probe_seconds gauge")
        for r in st["replicas"]:
            if r.get("last_probe_s") is not None:
                lines.append(
                    f'ptpu_router_replica_last_probe_seconds'
                    f'{{replica="{r["id"]}"}} {r["last_probe_s"]}')
        # Estimated per-replica host-clock skew (probe-derived —
        # an ESTIMATE, not device truth): the silent stitcher
        # correction, made visible and alertable.
        lines.append(
            "# TYPE ptpu_fleet_clock_skew_seconds gauge")
        for r in st["replicas"]:
            if r.get("clock_skew_s") is not None:
                lines.append(
                    f'ptpu_fleet_clock_skew_seconds'
                    f'{{replica="{r["id"]}"}} {r["clock_skew_s"]}')
        lines.append(
            "# TYPE ptpu_router_fleet_faults_applied_total counter")
        for site, n in sorted(st["fleet_faults_applied"].items()):
            lines.append(
                f'ptpu_router_fleet_faults_applied_total'
                f'{{site="{site}"}} {n}')
        # Router-side phase forensics families
        # (serving/forensics.py): route/attempt/backoff seconds +
        # shares, and the router sentry's anomaly counter.
        if self.forensics is not None:
            lines += self.forensics.metrics_lines("ptpu_router")
        return "\n".join(lines) + "\n"

    def info(self) -> Dict[str, Any]:
        return {
            "role": "router",
            "min_ready": self.min_ready,
            "max_attempts": self.max_attempts,
            "probe_interval_s": self.probe_interval_s,
            "probe_timeout_s": self.probe_timeout_s,
            "request_timeout_s": self.request_timeout_s,
            "hedge_min_s": self.hedge_min_s,
            "affinity": self.affinity_enabled,
            "affinity_max_outstanding":
                self.affinity_max_outstanding,
            "prefix_handoff": self.prefix_handoff_enabled,
            "disagg_min_tokens": self.disagg_min_tokens,
            "rebalance_every_s": self.rebalance_every_s,
            **self.stats(),
        }


def make_router_server(host: str, port: int,
                       router: ReplicaRouter) -> ThreadingHTTPServer:
    """The router's HTTP front (``ptpu route``): /generate and
    /prefill route to replicas; /healthz answers the SAME unified
    schema the replicas do (503 ``no_replica`` when rotation is
    empty, ``draining`` once drained); /metrics + /info render
    router.stats(); POST /fleet/restart starts the rolling restart.
    Fleet observability: GET /fleet/requests[/<id>] serves the
    router's request-span ring and the cross-tier stitched timeline,
    GET /fleet/metrics federates every replica's /metrics with
    ``replica=`` labels and fleet rollups."""

    class Handler(BaseHTTPRequestHandler):
        def _req_id(self) -> str:
            rid = sanitize_request_id(
                self.headers.get("X-Request-Id"))
            self._rid = rid or new_request_id()
            return self._rid

        def _send(self, code: int, obj: Dict[str, Any]) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Request-Id",
                             getattr(self, "_rid", None)
                             or new_request_id())
            self.end_headers()
            try:
                self.wfile.write(body)
            except OSError:
                pass

        def log_message(self, fmt, *args):
            pass

        def _send_text(self, body: bytes) -> None:
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except OSError:
                pass

        def _do_fleet_requests(self, path: str) -> None:
            """The router half of the request-debuggability surface:

            - ``GET /fleet/requests?status=&limit=`` — newest-first
              summaries from the router's terminal-record ring.
            - ``GET /fleet/requests/<id>`` — the STITCHED cross-tier
              causal timeline (router record + every involved
              replica's history record, clock-reconciled)."""
            if not router.history.enabled:
                self._send(400, {
                    "error": "router request history disabled "
                             "(start the router with "
                             "--request-history N)"})
                return
            if path in ("/fleet/requests", "/fleet/requests/"):
                q = parse_qs(urlparse(self.path).query)
                status = (q.get("status") or [None])[0]
                try:
                    limit = int((q.get("limit") or ["100"])[0])
                except ValueError:
                    self._send(400,
                               {"error": "limit must be an int"})
                    return
                self._send(200, {
                    "requests": router.history.list(status=status,
                                                    limit=limit),
                    **router.history.stats()})
                return
            want = path[len("/fleet/requests/"):]
            stitched = router.fleet_request(want)
            if stitched is None:
                self._send(404, {
                    "error": f"no router record for request "
                             f"{want!r} (never routed, or rolled "
                             f"off the "
                             f"{router.history.capacity}-record "
                             f"retention ring)"})
            else:
                self._send(200, stitched)

        def do_GET(self):
            self._req_id()
            path = urlparse(self.path).path
            if path == "/fleet/requests" \
                    or path.startswith("/fleet/requests/"):
                self._do_fleet_requests(path)
                return
            if self.path == "/healthz":
                ready = router._ready_count()
                if router.draining:
                    self._send(503, {"status": "unavailable",
                                     "reason": "draining"})
                elif ready == 0:
                    self._send(503, {"status": "unavailable",
                                     "reason": "no_replica",
                                     "replicas_ready": 0})
                else:
                    self._send(200, {"status": "ok",
                                     "role": "router",
                                     "replicas_ready": ready})
            elif self.path == "/info":
                self._send(200, router.info())
            elif self.path == "/metrics":
                self._send_text(router.metrics_text().encode())
            elif self.path == "/fleet/metrics":
                # Metrics federation: router + every replica's
                # /metrics (replica= labels) + fleet rollups, one
                # Prometheus scrape for the whole tier.
                self._send_text(router.fleet_metrics_text().encode())
            elif self.path == "/fleet/anomalies":
                # Forensics federation: router sentry findings merged
                # with every replica's /anomalies, ranked by score.
                self._send(200, router.fleet_anomalies())
            elif self.path == "/anomalies":
                if router.forensics is None:
                    self._send(400, {"error": "forensics disabled"})
                else:
                    self._send(200, router.forensics.report())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            rid = self._req_id()
            if self.path == "/fleet/restart":
                try:
                    state = router.fleet_restart()
                    self._send(200, {"started": True, **state})
                except RuntimeError as e:
                    self._send(409, {"error": str(e)})
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                return
            if self.path == "/drain":
                self._send(200, router.drain())
                return
            if self.path == "/fleet/prefix/rebalance":
                # One-copy-somewhere pass over the fleet's host
                # tiers; synchronous (scrapes + hints are bounded
                # HTTP exchanges) and idempotent.
                try:
                    self._send(200, router.fleet_prefix_rebalance())
                except Exception as e:
                    self._send(500, {
                        "error": f"{type(e).__name__}: {e}"})
                return
            if self.path not in ("/generate", "/prefill"):
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(
                        "request body must be a JSON object")
            except ValueError as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            try:
                if self.path == "/generate":
                    code, resp = router.route_generate(req, rid=rid)
                else:
                    code, resp = router.route_prefill(req, rid=rid)
            except Exception as e:  # never kill the router thread
                code, resp = 500, {
                    "error": f"{type(e).__name__}: {e}",
                    "request_id": rid}
            self._send(code, resp)

    class _RouterHTTPServer(ThreadingHTTPServer):
        request_queue_size = 128
        daemon_threads = True

    return _RouterHTTPServer((host, port), Handler)
