"""Serving package: the zoo's decode stack behind HTTP.

Split from the old single-module ``serving.py`` when the decode hot
path moved from request coalescing to continuous batching:

- ``server.py``    — ModelServer (validation, solo decode paths,
  prefix cache, metrics) + the stdlib HTTP front-end with bounded
  admission and 429 backpressure.
- ``engine.py``    — the continuous-batching decode engine: step-level
  scheduling over a fixed slot pool.
- ``slots.py``     — slot-indexed KV memory (stacked per-slot caches,
  the vmapped one-token step program).
- ``scheduler.py`` — admission queue, scheduler policy knobs, request
  and stream state.
- ``legacy.py``    — the seed request-coalescing path, kept as the
  measured A/B baseline (``batching="coalesce"``).
- ``telemetry.py`` — trace-span ring (+ ``GET /trace`` Chrome trace
  export), shared latency/acceptance histograms, and the
  single-flight ``jax.profiler`` wrapper behind ``POST
  /profile/start|stop``.

The public surface is unchanged: ``from polyaxon_tpu.serving import
ModelServer, make_server``.
"""

from .engine import DecodeEngine
from .scheduler import (DeadlineExceeded, PRIORITIES, QueueFullError,
                        RequestCancelled, SamplingSpec,
                        SchedulerPolicy, ShedError)
from .server import ModelServer, make_server
from .slots import SlotKVManager
from .telemetry import (Histogram, ProfileSession, Telemetry,
                        render_histogram)

__all__ = ["ModelServer", "make_server", "DecodeEngine",
           "SchedulerPolicy", "SamplingSpec", "SlotKVManager",
           "QueueFullError", "RequestCancelled", "DeadlineExceeded",
           "ShedError", "PRIORITIES", "Telemetry", "Histogram",
           "ProfileSession", "render_histogram"]
