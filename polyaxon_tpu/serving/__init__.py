"""Serving package: the zoo's decode stack behind HTTP.

Split from the old single-module ``serving.py`` when the decode hot
path moved from request coalescing to continuous batching:

- ``server.py``    — ModelServer (validation, solo decode paths,
  prefix cache, metrics) + the stdlib HTTP front-end with bounded
  admission and 429 backpressure.
- ``engine.py``    — the continuous-batching decode engine: step-level
  scheduling over a fixed slot pool.
- ``slots.py``     — slot-indexed KV memory (stacked per-slot caches,
  the vmapped one-token step program).
- ``paged.py``     — the PAGED slot KV manager (``kv_paged``): a
  refcounted pool of fixed-size KV pages with per-slot page tables
  and copy-on-write shared-prefix pages — same step bodies, storage
  bounded by token usage instead of slots × max_position.
- ``radix.py``     — compressed token-trie index behind the prefix
  cache (O(prompt) longest-match lookup, LRU + scan-resistant cold
  insertion, page-sharing ancestor lookup).
- ``scheduler.py`` — admission queue, scheduler policy knobs, request
  and stream state.
- ``legacy.py``    — the seed request-coalescing path, kept as the
  measured A/B baseline (``batching="coalesce"``).
- ``meshed.py``    — the serving mesh (``--mesh tp=4``): params under
  NamedSharding, KV pools sharded over the heads axis, the exact
  (reduction-free) layout whose meshed output is token-bitwise
  identical to unmeshed serving.
- ``profiling.py`` — the FLIGHT RECORDER (``--profile-every``):
  periodic single-flight ``jax.profiler`` windows over decode-step
  boundaries, auto-analyzed (analysis/xprof.py) into collective /
  transfer / host-gap / device-busy shares + a serving-MFU estimate,
  published as /metrics gauges and ``GET /profile/report``.
- ``telemetry.py`` — trace-span ring (+ ``GET /trace`` Chrome trace
  export), shared latency/acceptance histograms, and the
  single-flight ``jax.profiler`` wrapper behind ``POST
  /profile/start|stop``.
- ``debug.py``     — request-scoped debuggability: request IDs
  (``X-Request-Id`` honored/generated/echoed), the terminal-record
  retention ring behind ``GET /requests/<id>``, the published
  ``GET /debug/state`` snapshot board, and the stall watchdog
  (``--stall-timeout``) that dumps a diagnostic bundle when the
  engine wedges.
- ``faults.py``    — deterministic seeded fault injection
  (``--fault-plan``): site-keyed probes across the step dispatch,
  page allocation, the prefix store, the engine loop, and the HTTP
  handler — the chaos harness that proves recovery without changing
  a surviving token.
- ``router.py``    — the replica ROUTER tier (``ptpu route``): N
  replica endpoints behind one front — health-probed rotation with
  per-replica circuit breakers, least-outstanding + radix-prefix-
  affinity routing, failover with a bounded retry budget and
  cross-replica resume, hedged requests past the p99 watermark, and
  drain-aware rolling restarts (``POST /fleet/restart``).
- ``recovery.py``  — crash-only recovery: the shared bounded
  ``RetryPolicy``, the crash-storm ``CircuitBreaker`` (healthz 503
  ``engine_down`` instead of hangs), and the ``EngineSupervisor``
  that restarts a dead engine loop, rebuilds the pools without
  recompiling, and requeues every stream for token-identical
  resume.

The public surface is unchanged: ``from polyaxon_tpu.serving import
ModelServer, make_server``.
"""

from .debug import RequestHistory, StallWatchdog, new_request_id
from .engine import DecodeEngine
from .faults import FaultPlan
from .meshed import MeshError, ServingMesh, parse_mesh
from .paged import PagedSlotKVManager
from .radix import RadixPrefixIndex
from .recovery import CircuitBreaker, EngineSupervisor, RetryPolicy
from .router import (LocalReplica, Replica, ReplicaRouter,
                     RetryBudget, SLOTracker, make_router_server)
from .scheduler import (DeadlineExceeded, PRIORITIES,
                        PoisonedRequest, QueueFullError,
                        RequestCancelled, SamplingSpec,
                        SchedulerPolicy, ShedError)
from .server import (ModelServer, PrefixFetchPolicy,
                     make_server)
from .slots import SlotKVManager
from .telemetry import (Histogram, ProfileSession, Telemetry,
                        render_histogram)

__all__ = ["ModelServer", "PrefixFetchPolicy",
           "make_server", "DecodeEngine",
           "SchedulerPolicy", "SamplingSpec", "SlotKVManager",
           "PagedSlotKVManager", "RadixPrefixIndex",
           "ServingMesh", "parse_mesh", "MeshError",
           "QueueFullError", "RequestCancelled", "DeadlineExceeded",
           "ShedError", "PoisonedRequest", "PRIORITIES",
           "FaultPlan", "RetryPolicy", "CircuitBreaker",
           "EngineSupervisor",
           "ReplicaRouter", "Replica", "LocalReplica",
           "RetryBudget", "SLOTracker", "make_router_server",
           "Telemetry", "Histogram",
           "ProfileSession", "render_histogram",
           "RequestHistory", "StallWatchdog", "new_request_id"]
