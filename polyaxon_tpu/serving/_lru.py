"""Bounded LRU shared by the serving package's compile and prefix
caches — one recency/eviction policy, one place to change it."""

from collections import OrderedDict


def lru_get(cache: OrderedDict, key, cap: int, build):
    """Return ``cache[key]`` (refreshing its recency) or ``build()``,
    insert, and evict the least-recently-used entry past ``cap``."""
    if key in cache:
        cache.move_to_end(key)
        return cache[key]
    val = build()
    cache[key] = val
    if len(cache) > cap:
        cache.popitem(last=False)
    return val
