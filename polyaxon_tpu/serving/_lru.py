"""Bounded LRU shared by the serving package's compile and prefix
caches — one recency/eviction policy, one place to change it."""

from collections import OrderedDict


def lru_get(cache: OrderedDict, key, cap: int, build,
            sentinel=None, kind: str = "program"):
    """Return ``cache[key]`` (refreshing its recency) or ``build()``,
    insert, and evict the least-recently-used entry past ``cap``.

    ``sentinel`` (analysis.recompile.RecompileSentinel) makes
    hits/misses/evictions observable when the cache holds COMPILED
    PROGRAMS: a miss is a recompile, and steady-state traffic is
    supposed to produce none (the zero-recompile contract pinned in
    tests/test_analysis.py).  Value caches (the prefix KV store) pass
    no sentinel."""
    if key in cache:
        cache.move_to_end(key)
        if sentinel is not None:
            sentinel.hit(kind, key)
        return cache[key]
    if sentinel is not None:
        sentinel.miss(kind, key)
    val = build()
    cache[key] = val
    if len(cache) > cap:
        evicted_key, _ = cache.popitem(last=False)
        if sentinel is not None:
            sentinel.evicted(kind, evicted_key)
    return val
