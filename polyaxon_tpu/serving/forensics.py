"""Tail-latency forensics: the canonical phase ledger, rolling
per-phase baselines, and the anomaly sentry.

The observability stack records everything — lifecycle spans
(telemetry.py), terminal request records (debug.RequestHistory),
stitched fleet timelines (router.fleet_request) — but none of it
EXPLAINS a slow tail automatically: a p99 regression still means a
human reading Perfetto dumps.  This module is the explanation layer:

- **Phase ledger** (:func:`compute_ledger`,
  :func:`compute_router_ledger`): a closed-vocabulary decomposition
  of one request's wall time, computed from the SAME span tuples the
  history record and the ``timings`` block already carry.  The
  partition contract (docs/DESIGN.md): phases + explicit
  ``unattributed`` sum EXACTLY to the ledger's wall — internally the
  sweep works in integer microseconds, so the invariant is exact, not
  epsilon-approximate.  One shared function feeds the history record,
  the ``timings`` block, the stitched ``GET /fleet/requests/<id>``
  timeline, and the per-phase /metrics gauges — the surfaces cannot
  drift because there is only one computation.

- **Phase vocabulary**: the ``PHASE_*`` constants below are the ONLY
  legal phase names.  The PHASE-ENUM check (analysis/rules.py) flags
  phase-name string literals anywhere else in serving/, so engine,
  router, and report surfaces can never invent a divergent name.

- :class:`PhaseAccumulator` — cumulative per-phase seconds (the
  ``ptpu_serving_phase_seconds_total{phase=}`` counter family) plus
  the per-request share stream the sentry windows over.

- :class:`AnomalySentry` — rolling per-phase baselines (EWMA of
  window-mean shares + a windowed quantile band) with one-shot
  episode semantics borrowed from debug.StallWatchdog: the FIRST
  window where a phase's share breaks its band files a ranked
  finding, bumps ``ptpu_serving_anomalies_total{phase=}``, and (when
  a forensics directory is armed) writes a diagnostic bundle —
  offending exemplar timeline + state snapshot + trace tail — then
  stays quiet until the phase returns inside its band.

All host-side Python: no device work, no jax import, no lock shared
with the engine step — arming forensics cannot cost a recompile and
the bench's ``forensics_overhead`` leg pins the tax under the same
~3% contract as the telemetry layer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "PHASE_QUEUE_WAIT", "PHASE_DEVICE_LOCK_WAIT", "PHASE_PREFILL",
    "PHASE_ADMIT_WAIT", "PHASE_KV_WIRE_FETCH", "PHASE_KV_HANDOFF",
    "PHASE_DECODE", "PHASE_PREEMPT_GAP", "PHASE_FINALIZE",
    "PHASE_ROUTE_PICK", "PHASE_REPLICA_ATTEMPT",
    "PHASE_PREFILL_REMOTE", "PHASE_RETRY_BACKOFF",
    "PHASE_UNATTRIBUTED", "PHASES", "ROUTER_PHASES",
    "compute_ledger", "compute_router_ledger", "ledger_shares",
    "is_solo_events", "PhaseAccumulator", "AnomalySentry",
    "ForensicsCore",
]

# -- the closed phase vocabulary ---------------------------------------
#
# Replica-side phases (engine + solo paths):
PHASE_QUEUE_WAIT = "queue_wait"          # admission queue (engine)
PHASE_DEVICE_LOCK_WAIT = "device_lock_wait"  # solo-path lock wait
PHASE_PREFILL = "prefill"                # prefill chunk compute
PHASE_ADMIT_WAIT = "admit_wait"          # prefilled, waiting for a
#                                          slot / between own chunks
PHASE_KV_WIRE_FETCH = "kv_wire_fetch"    # cross-replica KV pull
PHASE_KV_HANDOFF = "kv_handoff"          # disagg prefill KV ingest
PHASE_DECODE = "decode"                  # decode residency
PHASE_PREEMPT_GAP = "preempt_gap"        # evicted, waiting to resume
PHASE_FINALIZE = "finalize"              # last event -> wall end
# Router-side phases:
PHASE_ROUTE_PICK = "route_pick"          # arrival -> first send
PHASE_REPLICA_ATTEMPT = "replica_attempt"  # send/recv bracket
PHASE_PREFILL_REMOTE = "prefill_remote"  # disagg stage-1 prefill
PHASE_RETRY_BACKOFF = "retry_backoff"    # between attempts
# Shared:
PHASE_UNATTRIBUTED = "unattributed"      # the explicit remainder

# Canonical order — ledgers, /metrics families, and reports all
# iterate THIS tuple, so exposition order is pinned.
PHASES: Tuple[str, ...] = (
    PHASE_QUEUE_WAIT, PHASE_DEVICE_LOCK_WAIT, PHASE_PREFILL,
    PHASE_ADMIT_WAIT, PHASE_KV_WIRE_FETCH, PHASE_KV_HANDOFF,
    PHASE_DECODE, PHASE_PREEMPT_GAP, PHASE_FINALIZE,
    PHASE_ROUTE_PICK, PHASE_REPLICA_ATTEMPT, PHASE_PREFILL_REMOTE,
    PHASE_RETRY_BACKOFF, PHASE_UNATTRIBUTED,
)

ROUTER_PHASES: Tuple[str, ...] = (
    PHASE_ROUTE_PICK, PHASE_REPLICA_ATTEMPT, PHASE_PREFILL_REMOTE,
    PHASE_RETRY_BACKOFF, PHASE_FINALIZE, PHASE_UNATTRIBUTED,
)

# Span name -> phase, replica side.  "queue" is context-dependent:
# on the engine path it is admission-queue wait, on the solo path it
# brackets the device-lock wait (compute_ledger's ``solo`` flag).
_SPAN_PHASES = {
    "queue": PHASE_QUEUE_WAIT,
    "prefill": PHASE_PREFILL,
    "decode": PHASE_DECODE,
    "solo_decode": PHASE_DECODE,
    "coalesce_decode": PHASE_DECODE,
    "prefix_solo": PHASE_DECODE,
    "prefix_wire_fetch": PHASE_KV_WIRE_FETCH,
    "kv_handoff": PHASE_KV_HANDOFF,
    "prefix_handoff": PHASE_KV_HANDOFF,
}

# Overlap priority (higher wins the elementary segment): the wire
# phases beat the fused solo decode span that brackets them; active
# compute (prefill) beats a concurrent sibling stream's decode
# residency; queue wait loses to everything (it brackets nothing but
# waiting).
_SPAN_PRIO = {
    PHASE_KV_WIRE_FETCH: 6, PHASE_KV_HANDOFF: 6,
    PHASE_PREFILL: 5, PHASE_DECODE: 4,
    PHASE_QUEUE_WAIT: 2, PHASE_DEVICE_LOCK_WAIT: 2,
}

_ROUTER_SPAN_PHASES = {
    "attempt": PHASE_REPLICA_ATTEMPT,
    "prefill_remote": PHASE_PREFILL_REMOTE,
}
_ROUTER_SPAN_PRIO = {
    PHASE_PREFILL_REMOTE: 5, PHASE_REPLICA_ATTEMPT: 4,
}

# Span names whose presence marks a SOLO-path event stream (no
# admission queue; the "queue" span is the device-lock wait).
_SOLO_MARKERS = frozenset(
    {"solo_decode", "coalesce_decode", "prefix_solo"})


def is_solo_events(names) -> bool:
    """True when an event-name iterable carries a solo-path marker
    span — offline consumers (trace_report) use this to pick the
    right ``solo`` flag for :func:`compute_ledger`."""
    return any(n in _SOLO_MARKERS for n in names)


def _gap_phase(prev: Optional[str], trailing: bool) -> str:
    """Classify an uncovered segment by its LEFT neighbor: after a
    prefill chunk the stream is waiting to be admitted (or for its
    next chunk's turn); after a non-final decode span it was evicted
    and is waiting to resume; the trailing gap is response finalize;
    anything else — including the leading gap, and a request with NO
    covered spans at all — stays honest as unattributed."""
    if trailing:
        return PHASE_FINALIZE if prev is not None \
            else PHASE_UNATTRIBUTED
    if prev == PHASE_PREFILL:
        return PHASE_ADMIT_WAIT
    if prev == PHASE_DECODE:
        return PHASE_PREEMPT_GAP
    return PHASE_UNATTRIBUTED


def _router_gap_phase(prev: Optional[str], trailing: bool) -> str:
    if trailing:
        return PHASE_FINALIZE if prev is not None \
            else PHASE_UNATTRIBUTED
    if prev is None or prev == PHASE_PREFILL_REMOTE:
        return PHASE_ROUTE_PICK
    if prev == PHASE_REPLICA_ATTEMPT:
        return PHASE_RETRY_BACKOFF
    return PHASE_UNATTRIBUTED


def _sweep(events, t0: float, t1: float,
           span_phases: Dict[str, str], prio: Dict[str, int],
           gap_phase: Callable[[Optional[str], bool], str],
           queue_phase: str) -> Dict[str, Any]:
    """The shared partition sweep.  ``events`` are ``(name, a, b,
    args)`` span tuples; the ledger window is ``[min(t0, earliest
    event), max(t1, latest event)]`` (caller-paid work — a prefix
    wire fetch — legally precedes submission).  Every elementary
    segment is attributed to the highest-priority covering span, or
    to a gap phase classified by its left neighbor.  Accounting is
    integer microseconds, so phases + unattributed == wall EXACTLY.
    """
    w0, w1 = float(t0), float(t1)
    intervals: List[Tuple[float, float, str]] = []
    for name, a, b, _args in events or ():
        ph = span_phases.get(name)
        if ph == PHASE_QUEUE_WAIT:
            ph = queue_phase
        if ph is None or b <= a:
            continue            # instants and foreign spans: no time
        w0 = min(w0, a)
        w1 = max(w1, b)
        intervals.append((a, b, ph))
    wall_us = max(0, round((w1 - w0) * 1e6))
    totals_us: Dict[str, int] = {}
    if wall_us:
        cuts = {0, wall_us}
        iv_us = []
        for a, b, ph in intervals:
            a_us = min(wall_us, max(0, round((a - w0) * 1e6)))
            b_us = min(wall_us, max(0, round((b - w0) * 1e6)))
            if b_us > a_us:
                iv_us.append((a_us, b_us, ph))
                cuts.add(a_us)
                cuts.add(b_us)
        edges = sorted(cuts)
        prev_cover: Optional[str] = None
        pending_gap = 0          # contiguous uncovered run, in us
        gap_left = prev_cover
        for i in range(len(edges) - 1):
            s, e = edges[i], edges[i + 1]
            cover, cover_prio = None, -1
            for a_us, b_us, ph in iv_us:
                if a_us <= s and b_us >= e:
                    p = prio.get(ph, 0)
                    if p > cover_prio:
                        cover, cover_prio = ph, p
            if cover is None:
                if pending_gap == 0:
                    gap_left = prev_cover
                pending_gap += e - s
            else:
                if pending_gap:
                    gp = gap_phase(gap_left, False)
                    totals_us[gp] = totals_us.get(gp, 0) \
                        + pending_gap
                    pending_gap = 0
                totals_us[cover] = totals_us.get(cover, 0) + (e - s)
                prev_cover = cover
        if pending_gap:
            gp = gap_phase(gap_left, True)
            totals_us[gp] = totals_us.get(gp, 0) + pending_gap
    unattr_us = totals_us.pop(PHASE_UNATTRIBUTED, 0)
    unattr_us += wall_us - (sum(totals_us.values()) + unattr_us)
    if unattr_us < 0:            # defensive: cannot happen, the
        unattr_us = 0            # sweep partitions by construction
    phases = {ph: totals_us[ph] / 1e6
              for ph in PHASES if totals_us.get(ph)}
    ledger: Dict[str, Any] = {
        "wall_s": wall_us / 1e6,
        "phases": phases,
        "unattributed": unattr_us / 1e6,
    }
    ranked = sorted(phases.items(), key=lambda kv: -kv[1])
    if ranked and ranked[0][1] >= unattr_us / 1e6:
        ledger["dominant"] = ranked[0][0]
    elif wall_us:
        ledger["dominant"] = PHASE_UNATTRIBUTED
    return ledger


def compute_ledger(events, t0: float, t1: float, *,
                   solo: bool = False) -> Dict[str, Any]:
    """The replica-side phase ledger for one request: ``events`` are
    the ``(name, a, b, args)`` span tuples a stream (or the union of
    a group's streams) collected, ``[t0, t1]`` the submit->done
    bracket.  ``solo=True`` maps the "queue" span to device-lock
    wait (the solo/coalesce paths queue on the lock, not the
    admission queue)."""
    return _sweep(events, t0, t1, _SPAN_PHASES, _SPAN_PRIO,
                  _gap_phase,
                  PHASE_DEVICE_LOCK_WAIT if solo
                  else PHASE_QUEUE_WAIT)


def compute_router_ledger(events, t0: float,
                          t1: float) -> Dict[str, Any]:
    """The router-side ledger over a request's route trace: attempt
    send/receive brackets, disagg stage-1 prefill, and the gaps
    between them (route pick, retry backoff)."""
    return _sweep(events, t0, t1, _ROUTER_SPAN_PHASES,
                  _ROUTER_SPAN_PRIO, _router_gap_phase,
                  PHASE_QUEUE_WAIT)


def ledger_shares(ledger: Dict[str, Any]) -> Dict[str, float]:
    """Per-phase share of the ledger's wall (unattributed included);
    empty when wall is zero."""
    wall = float(ledger.get("wall_s") or 0.0)
    if wall <= 0:
        return {}
    out = {ph: v / wall
           for ph, v in (ledger.get("phases") or {}).items()}
    un = float(ledger.get("unattributed") or 0.0)
    if un > 0:
        out[PHASE_UNATTRIBUTED] = un / wall
    return out


class PhaseAccumulator:
    """Cumulative per-phase seconds + wall across every noted
    request — the /metrics per-phase family source.  Thread-safe
    (noted from handler and engine threads)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {}
        self._wall_s = 0.0
        self.requests_total = 0

    def add(self, ledger: Dict[str, Any]) -> None:
        wall = float(ledger.get("wall_s") or 0.0)
        un = float(ledger.get("unattributed") or 0.0)
        with self._lock:
            self.requests_total += 1
            self._wall_s += wall
            for ph, v in (ledger.get("phases") or {}).items():
                self._seconds[ph] = self._seconds.get(ph, 0.0) + v
            if un:
                self._seconds[PHASE_UNATTRIBUTED] = \
                    self._seconds.get(PHASE_UNATTRIBUTED, 0.0) + un

    def totals(self) -> Dict[str, float]:
        """{phase: cumulative seconds} in canonical order."""
        with self._lock:
            return {ph: round(self._seconds[ph], 6)
                    for ph in PHASES if ph in self._seconds}

    def shares(self) -> Dict[str, float]:
        """{phase: cumulative share of total wall} — the fleet-
        rollup gauge family (a gauge, so the federation layer adds
        min/max spread across replicas)."""
        with self._lock:
            if self._wall_s <= 0:
                return {}
            return {ph: round(self._seconds[ph] / self._wall_s, 6)
                    for ph in PHASES if ph in self._seconds}

    def wall_total_s(self) -> float:
        with self._lock:
            return round(self._wall_s, 6)


class AnomalySentry:
    """Rolling per-phase share baselines + the band detector.

    Requests arrive one ledger at a time (:meth:`note`); every
    ``window`` requests close a WINDOW whose per-phase mean shares
    are compared against the baseline built from PRIOR windows —
    an EWMA of window means plus the high quantile of the retained
    window history.  A phase breaks its band when its window share
    exceeds ``max(ratio * ewma, q_hi + margin)`` AND an absolute
    floor (``min_share`` — a phase that grew from 0.1% to 0.4% of
    wall is noise, not an incident).  Detection stays disarmed until
    ``baseline_windows`` windows exist, so short steady runs can
    never false-positive.

    Episode semantics (StallWatchdog's): the first breaking window
    files ONE finding (counter bump + optional on-disk bundle); the
    episode re-arms when a later window puts the phase back inside
    its band."""

    def __init__(self, *, window: int = 64,
                 baseline_windows: int = 4,
                 history_windows: int = 32,
                 ratio: float = 2.0, margin: float = 0.1,
                 min_share: float = 0.05, alpha: float = 0.3,
                 quantile: float = 0.9,
                 max_findings: int = 32,
                 out_dir: Optional[str] = None,
                 snapshot_fn: Optional[Callable[[], Any]] = None,
                 trace_tail_fn: Optional[Callable[[], Any]] = None,
                 record_fn: Optional[
                     Callable[[str], Any]] = None):
        if window <= 0:
            raise ValueError(
                f"sentry window must be > 0; got {window}")
        self.window = int(window)
        self.baseline_windows = int(baseline_windows)
        self.ratio = float(ratio)
        self.margin = float(margin)
        self.min_share = float(min_share)
        self.alpha = float(alpha)
        self.quantile = float(quantile)
        self.out_dir = out_dir
        self.snapshot_fn = snapshot_fn
        self.trace_tail_fn = trace_tail_fn
        self.record_fn = record_fn
        self._lock = threading.Lock()
        self._cur: List[Dict[str, float]] = []
        # Worst offender per phase inside the current window:
        # {phase: (share, rid)} — the finding's exemplar.
        self._cur_worst: Dict[str, Tuple[float, Optional[str]]] = {}
        self._hist: "deque[Dict[str, float]]" = deque(
            maxlen=max(1, int(history_windows)))
        self._ewma: Dict[str, float] = {}
        self._active: set = set()      # phases inside an episode
        self.windows_closed = 0
        self.anomalies_total: Dict[str, int] = {}
        self.flagged_total = 0
        self.bundles_written = 0
        self._findings: "deque[Dict[str, Any]]" = deque(
            maxlen=max(1, int(max_findings)))

    # -- ingest ---------------------------------------------------------

    def note(self, ledger: Dict[str, Any],
             rid: Optional[str] = None) -> List[Dict[str, Any]]:
        """Feed one request's ledger; returns the findings the
        closing window produced (empty for most calls)."""
        shares = ledger_shares(ledger)
        if not shares:
            return []
        with self._lock:
            self._cur.append(shares)
            for ph, sh in shares.items():
                worst = self._cur_worst.get(ph)
                if worst is None or sh > worst[0]:
                    self._cur_worst[ph] = (sh, rid)
            if len(self._cur) < self.window:
                return []
            return self._close_window()

    def _close_window(self) -> List[Dict[str, Any]]:
        # Called under self._lock with a full window.
        n = len(self._cur)
        wmean: Dict[str, float] = {}
        for shares in self._cur:
            for ph, sh in shares.items():
                wmean[ph] = wmean.get(ph, 0.0) + sh
        wmean = {ph: v / n for ph, v in wmean.items()}
        worst = dict(self._cur_worst)
        self._cur = []
        self._cur_worst = {}
        findings: List[Dict[str, Any]] = []
        armed = self.windows_closed >= self.baseline_windows
        if armed:
            findings = self._detect(wmean, worst)
        # Baseline update AFTER detection — the offending window
        # must not vouch for itself.
        for ph, v in wmean.items():
            prev = self._ewma.get(ph)
            self._ewma[ph] = v if prev is None else \
                self.alpha * v + (1 - self.alpha) * prev
        self._hist.append(wmean)
        self.windows_closed += 1
        return findings

    def _band_hi(self, phase: str) -> float:
        vals = sorted(h.get(phase, 0.0) for h in self._hist)
        if not vals:
            return 0.0
        i = min(len(vals) - 1,
                int(self.quantile * (len(vals) - 1) + 0.999999))
        return vals[i]

    def _detect(self, wmean: Dict[str, float],
                worst: Dict[str, Tuple[float, Optional[str]]]
                ) -> List[Dict[str, Any]]:
        findings: List[Dict[str, Any]] = []
        for ph in PHASES:
            share = wmean.get(ph, 0.0)
            ewma = self._ewma.get(ph, 0.0)
            band_hi = self._band_hi(ph)
            breaking = (share >= self.min_share
                        and share > self.ratio * ewma
                        and share > band_hi + self.margin)
            if not breaking:
                self._active.discard(ph)     # re-arm the episode
                continue
            if ph in self._active:
                continue                     # one-shot per episode
            self._active.add(ph)
            self.flagged_total += 1
            self.anomalies_total[ph] = \
                self.anomalies_total.get(ph, 0) + 1
            w_share, w_rid = worst.get(ph, (share, None))
            finding = {
                "phase": ph,
                "share": round(share, 6),
                "baseline_ewma": round(ewma, 6),
                "band_hi": round(band_hi, 6),
                "score": round(share - ewma, 6),
                "window": self.windows_closed,
                "window_requests": self.window,
                "worst_share": round(w_share, 6),
                "t": round(time.time(), 3),
                **({"exemplars": [w_rid]} if w_rid else {}),
            }
            path = self._write_bundle(finding)
            if path:
                finding["bundle"] = path
            findings.append(finding)
            self._findings.append(finding)
        findings.sort(key=lambda f: -f["score"])
        return findings

    # -- the bundle -----------------------------------------------------

    def _write_bundle(self, finding: Dict[str, Any]
                      ) -> Optional[str]:
        if self.out_dir is None:
            return None
        bundle: Dict[str, Any] = {"anomaly": finding}
        if self.snapshot_fn is not None:
            try:
                bundle["state"] = self.snapshot_fn()
            except Exception as e:
                bundle["state"] = {
                    "error": f"{type(e).__name__}: {e}"}
        if self.record_fn is not None:
            recs = {}
            for rid in finding.get("exemplars", []):
                try:
                    recs[rid] = self.record_fn(rid)
                except Exception as e:
                    recs[rid] = {
                        "error": f"{type(e).__name__}: {e}"}
            if recs:
                bundle["exemplar_records"] = recs
        if self.trace_tail_fn is not None:
            try:
                bundle["trace_tail"] = self.trace_tail_fn()
            except Exception:
                bundle["trace_tail"] = []
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"anomaly_{self.flagged_total}_{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1, default=str)
            self.bundles_written += 1
            return path
        except Exception:
            # A read-only disk must not kill detection — the
            # finding and the counter still surface the episode.
            import logging

            logging.getLogger(__name__).warning(
                "anomaly bundle write failed (finding kept)",
                exc_info=True)
            return None

    # -- introspection --------------------------------------------------

    def findings(self) -> List[Dict[str, Any]]:
        """Retained findings, highest score first."""
        with self._lock:
            return sorted((dict(f) for f in self._findings),
                          key=lambda f: -f.get("score", 0.0))

    def baseline(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "windows_closed": self.windows_closed,
                "window_requests": self.window,
                "armed": self.windows_closed
                >= self.baseline_windows,
                "ewma_share": {ph: round(self._ewma[ph], 6)
                               for ph in PHASES
                               if ph in self._ewma},
                "active_episodes": sorted(self._active),
            }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "window_requests": self.window,
                "baseline_windows": self.baseline_windows,
                "ratio": self.ratio,
                "margin": self.margin,
                "min_share": self.min_share,
                "windows_closed": self.windows_closed,
                "anomalies_total": dict(self.anomalies_total),
                "flagged_total": self.flagged_total,
                "bundles_written": self.bundles_written,
                **({"dir": self.out_dir}
                   if self.out_dir is not None else {}),
            }


class ForensicsCore:
    """One replica's (or the router's) forensics state: the phase
    accumulator + the anomaly sentry, behind a single ``note``.
    ``ModelServer`` and ``Router`` each own one; a ``None`` core is
    the whole layer's off switch (one attribute check per request —
    the same contract as the trace ring and the history ring)."""

    def __init__(self, **sentry_kwargs):
        self.accumulator = PhaseAccumulator()
        self.sentry = AnomalySentry(**sentry_kwargs)

    def note(self, ledger: Dict[str, Any],
             rid: Optional[str] = None) -> List[Dict[str, Any]]:
        self.accumulator.add(ledger)
        return self.sentry.note(ledger, rid)

    def metrics_lines(self, prefix: str) -> List[str]:
        """The per-phase /metrics families: cumulative seconds
        (counter), wall share (gauge), anomaly episodes (counter).
        TYPE lines render unconditionally — the labeled-family
        idiom, so a scraper sees the family before first traffic."""
        lines = [f"# TYPE {prefix}_phase_seconds_total counter"]
        for ph, v in self.accumulator.totals().items():
            lines.append(
                f'{prefix}_phase_seconds_total{{phase="{ph}"}} {v}')
        lines.append(f"# TYPE {prefix}_phase_share gauge")
        for ph, v in self.accumulator.shares().items():
            lines.append(
                f'{prefix}_phase_share{{phase="{ph}"}} {v}')
        lines.append(f"# TYPE {prefix}_anomalies_total counter")
        with self.sentry._lock:
            totals = dict(self.sentry.anomalies_total)
        for ph in PHASES:
            if ph in totals:
                lines.append(
                    f'{prefix}_anomalies_total{{phase="{ph}"}} '
                    f"{totals[ph]}")
        return lines

    def report(self) -> Dict[str, Any]:
        """The ``GET /anomalies`` body."""
        return {
            "findings": self.sentry.findings(),
            "baseline": self.sentry.baseline(),
            "sentry": self.sentry.status(),
            "phase_share": self.accumulator.shares(),
            "phase_seconds_total": self.accumulator.totals(),
            "requests_total": self.accumulator.requests_total,
        }
