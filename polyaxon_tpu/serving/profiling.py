"""Flight recorder: periodic profiler windows with device-truth
attribution, published live.

PR 4 gave the server manual ``POST /profile/start|stop`` and left the
operator staring at Perfetto; the host-side step timings everywhere
else (``SlotKVManager.last_step_device_s``, ``step_device_share``)
are perf_counter deltas around a blocking sync — ESTIMATES that
conflate dispatch overhead, host gaps, and real device work.  This
module closes the loop:

- :class:`FlightRecorder` (armed by ``ptpu serve --profile-every N
  --profile-steps K``, OFF by default) wraps K decode-step
  boundaries in a single-flight ``jax.profiler`` window every N
  dispatches, analyzes the dump on a background thread through the
  trace parser (analysis/xprof.py), and publishes the latest
  attribution record — collective share, transfer share, host-gap
  (bubble) share, device-busy fraction, and serving MFU — as
  ``/metrics`` gauges, an ``/info`` ``profiling`` block, and the
  ``GET /profile/report`` JSON.  ONE reduction feeds all three
  surfaces (the published record is the report), so they can never
  drift.
- :func:`decode_flops_per_token` is the per-model forward-only flop
  estimate behind the MFU number: the same analytic closed forms the
  MFU benches use (models/registry.py ``*_train_flops``), at 2N
  instead of 6N (no backward pass) plus the position-dependent
  attention term.  Serving MFU = tokens committed in the window x
  flops/token / (window wall x peak flops x devices); the caveats —
  analytic dense count, mean-position attention, nominal peak on
  unknown hardware — ride the record as ``peak_flops_source`` /
  ``flops_model`` so nobody mistakes the number for a measured
  hardware counter (docs/SERVING.md "Observability").

Engine-thread cost when disabled: ``engine.recorder is None`` — one
attribute check per dispatch.  When armed, the off-window cost is one
integer bump per dispatch; the per-window cost (start/stop_trace +
dump IO) is bounded by the bench's recorder-overhead A/B leg (<= 3%
agg tok/s, benchmarks/bench_serving_load.py).  The first
``start_trace`` of a process pays several seconds of profiler-library
init, so the recorder PRIMES the profiler at construction — at server
startup, never at a traffic-carrying boundary.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .telemetry import ENGINE_PID

__all__ = ["FlightRecorder", "decode_flops_per_token",
           "detect_peak_flops", "NOMINAL_PEAK_FLOPS"]

# Per-chip bf16 peaks for the TPU generations the repo benches
# (mirrors bench.chip_peak_flops — duplicated here because bench.py
# is a script with import-time backend probing, not a library).
_PEAK_BF16 = (("v5litepod", 197e12), ("v5e", 197e12),
              ("v5p", 459e12), ("v4", 275e12), ("v3", 123e12),
              ("v2", 45e12))

# Unknown hardware (the CPU smoke): a NOMINAL 1 TF/s peak so the MFU
# gauge stays finite and comparable run-to-run on one machine.  The
# record labels it ``peak_flops_source: "nominal"`` — it is a
# utilization TREND there, never a hardware claim.
NOMINAL_PEAK_FLOPS = 1e12


def detect_peak_flops() -> Dict[str, Any]:
    """``{"peak_flops": per-chip peak, "peak_flops_source":
    "device"|"nominal"}`` for the current backend."""
    try:
        import jax

        kind = (getattr(jax.devices()[0], "device_kind", "")
                or "").lower()
    except Exception:
        kind = ""
    if "tpu" in kind:
        for key, peak in _PEAK_BF16:
            if key in kind:
                return {"peak_flops": peak,
                        "peak_flops_source": "device"}
        return {"peak_flops": 197e12, "peak_flops_source": "device"}
    return {"peak_flops": NOMINAL_PEAK_FLOPS,
            "peak_flops_source": "nominal"}


def decode_flops_per_token(cfg, position: float) -> Optional[float]:
    """Analytic FORWARD flops to decode ONE token at context length
    ``position`` for a decoder-only transformer config, mirroring the
    registry's train-flop conventions at fwd-only cost (2N dense, not
    6N; attention 4*L*position*h fwd, no causal halving — a decode
    step attends to exactly its prefix):

    - dense: 2 * N_matmul (qkv/o/mlp kernels + lm head; embedding
      lookups are gathers and excluded);
    - llama-style (head_dim + num_kv_heads + intermediate_size):
      GQA-shrunk k/v projections and the 3-matmul SwiGLU, exactly as
      ``_llama_train_flops``;
    - MoE (num_experts): one expert MLP per token + the router, as
      ``_moe_train_flops``.

    Returns None for configs the estimate doesn't speak (encoders,
    seq2seq) — MFU is then omitted rather than invented."""
    h = getattr(cfg, "hidden_size", None)
    layers = getattr(cfg, "num_layers", None)
    vocab = getattr(cfg, "vocab_size", None)
    if not h or not layers or not vocab \
            or hasattr(cfg, "d_model") or hasattr(cfg, "num_classes"):
        return None
    head_dim = getattr(cfg, "head_dim", None)
    kv_heads = getattr(cfg, "num_kv_heads", None)
    inter = getattr(cfg, "intermediate_size", None)
    if head_dim and kv_heads and inter:
        per_layer = (2 * h * h + 2 * h * kv_heads * head_dim
                     + 3 * h * inter)
    else:
        per_layer = 4 * h * h + 2 * h * (inter or 4 * h)
    n_experts = getattr(cfg, "num_experts", 0) or 0
    n_matmul = layers * (per_layer + h * n_experts) + h * vocab
    attn = 4.0 * layers * max(0.0, float(position)) * h
    return 2.0 * n_matmul + attn


class FlightRecorder:
    """Periodic single-flight profiler windows over the decode loop.

    The ENGINE THREAD drives :meth:`on_step_start` /
    :meth:`on_step_end` around every decode dispatch (engine.py);
    window analysis runs on a background thread; readers
    (``/metrics``, ``/info``, ``GET /profile/report``) take the
    published record under ``_lock``.  Windows share the server's
    :class:`~.telemetry.ProfileSession`, so a manual
    ``POST /profile/start`` and a recorder window can never race
    ``jax.profiler``'s process-global state: whoever starts first
    owns the session (the other side gets a 409 / skips-and-retries
    at the next boundary)."""

    def __init__(self, session, *, every: int, steps: int = 8,
                 telemetry=None,
                 flops_fn: Optional[Callable[[float],
                                             Optional[float]]] = None,
                 peak_flops: Optional[float] = None,
                 peak_flops_source: str = "device",
                 n_devices: int = 1,
                 position_probe: Optional[Callable[[], float]] = None,
                 history: int = 16, prime: bool = True,
                 max_window_s: float = 10.0):
        if every < 1:
            raise ValueError(f"profile_every must be >= 1; got "
                             f"{every}")
        if steps < 1:
            raise ValueError(f"profile_steps must be >= 1; got "
                             f"{steps}")
        if max_window_s <= 0:
            raise ValueError(f"max_window_s must be > 0; got "
                             f"{max_window_s}")
        self.session = session
        self.every = int(every)
        self.steps = int(steps)
        self.tel = telemetry
        self.flops_fn = flops_fn
        if peak_flops is None:
            d = detect_peak_flops()
            peak_flops = d["peak_flops"]
            peak_flops_source = d["peak_flops_source"]
        self.peak_flops = float(peak_flops)
        self.peak_flops_source = peak_flops_source
        self.n_devices = max(1, int(n_devices))
        self.position_probe = position_probe
        self.max_window_s = float(max_window_s)
        self._lock = threading.Lock()
        # Window open/close transitions: normally engine-thread-only
        # (on_step_start/on_step_end), but the per-window watchdog
        # timer and close() also end windows, so every transition
        # goes under this lock.  Uncontended acquire is ~100ns next
        # to a multi-ms dispatch; the recorder-overhead bench leg
        # holds the total.
        self._window_lock = threading.Lock()
        self._latest: Optional[Dict[str, Any]] = None
        self._windows: "deque[Dict[str, Any]]" = deque(
            maxlen=max(1, history))
        self._window: Optional[Dict[str, Any]] = None
        self._since = 0
        self.windows_total = 0      # windows OPENED (engine thread)
        self.windows_analyzed = 0   # records published
        self.windows_skipped = 0    # boundary hit while a MANUAL
        #                             profile owned the session
        self.windows_deferred = 0   # boundary hit while our own
        #                             previous window's async stop
        #                             was still in flight (retried
        #                             at the very next boundary)
        self.last_error: Optional[str] = None
        self._failed_dump: Optional[str] = None
        self._analyzer: Optional[threading.Thread] = None
        if prime:
            self._prime()

    def _prime(self) -> None:
        """Pay the profiler library's first-``start_trace`` init
        (seconds) HERE, at construction on the slow startup path —
        never at a traffic-carrying step boundary."""
        try:
            self.session.start(owner="recorder-prime",
                               python_tracer=False)
            d = self.session.stop(owner="recorder-prime")
            # The prime's dump carries no traffic — same disk
            # discipline as analyzed windows (one orphan per server
            # start adds up across rolling deploys).
            if d:
                self._discard_dump(d)
        except Exception as e:
            # A broken profiler backend disables the recorder's
            # windows (every start will fail the same way) but must
            # not kill the server.
            self.last_error = f"prime: {type(e).__name__}: {e}"

    # -- engine-thread hooks --------------------------------------------

    def on_step_start(self) -> None:
        """Called immediately BEFORE a decode dispatch.  Opens a
        window when the cadence is due and the profiler session is
        free (a manual profile in flight defers the window to a later
        boundary instead of erroring); on in-window boundaries it
        samples the pool's mean decode position — BEFORE the
        dispatch, while the streams it measures are still resident —
        for the MFU attention term."""
        with self._window_lock:
            if self._window is not None:
                self._probe_position(self._window)
                return
            self._since += 1
            if self._since < self.every:
                return
            try:
                # python_tracer=False: the recorder's windows must
                # not instrument every Python call on every server
                # thread — device/runtime events + ptpu_step markers
                # are the attribution inputs (see
                # ProfileSession.start).
                d = self.session.start(owner="recorder",
                                       python_tracer=False)
            except RuntimeError:
                if getattr(self.session, "owner", None) \
                        == "recorder":
                    # Our OWN previous window's async stop is still
                    # in flight — not a manual profile.  Retry at
                    # the very next boundary (the stop completes in
                    # ms) instead of paying a full cadence and
                    # mislabeling the miss as operator activity.
                    self.windows_deferred += 1
                    self._since = self.every
                else:
                    self.windows_skipped += 1
                    self._since = 0  # full cadence before retrying
                return
            except Exception as e:
                # A filesystem/profiler failure opening the window
                # (--profile-dir volume gone read-only, ...) must
                # never escape into the engine tick — it would fail
                # every in-flight request, every N dispatches.
                # Record it and retry at the next cadence (the
                # volume may come back).  last_error is elsewhere
                # written (and always read) under _lock by the
                # analyzer thread — this engine-thread write must
                # agree on the lock or it can vanish under a
                # concurrent _analyze success-clear.
                with self._lock:
                    self.last_error = \
                        f"start: {type(e).__name__}: {e}"
                self.windows_skipped += 1
                self._since = 0
                return
            self._since = 0
            self.windows_total += 1
            w = {"window": self.windows_total, "trace_dir": d,
                 "t0": time.perf_counter(), "steps": 0,
                 "tokens": 0, "pos_sum": 0.0, "pos_n": 0}
            # Watchdog: the engine only reaches on_step_end while
            # traffic flows — if the queue drains mid-window, NO
            # boundary ever closes it, the trace collects forever,
            # and every manual /profile/start 409s against a window
            # that will never end.  The timer force-closes an
            # overdue window (record honestly marked
            # deadline_closed, attribution still anchored to the
            # steps that actually ran).
            t = threading.Timer(self.max_window_s,
                                self._deadline_close,
                                args=(self.windows_total,))
            t.daemon = True
            w["_timer"] = t
            self._window = w
            self._probe_position(w)
            t.start()
        if self.tel is not None:
            self.tel.instant(0, "profile_window_start",
                             time.perf_counter(),
                             pid=ENGINE_PID, id=w["window"])

    def _probe_position(self, w: Dict[str, Any]) -> None:
        if self.position_probe is None:
            return
        try:
            w["pos_sum"] += float(self.position_probe())
            w["pos_n"] += 1
        except Exception:
            # The probe is advisory (it only feeds the MFU attention
            # term); a failure must never break a step boundary.
            import logging

            logging.getLogger(__name__).debug(
                "position probe failed", exc_info=True)

    def on_step_end(self, tokens: int) -> None:
        """Called after a decode dispatch commits; ``tokens`` is the
        number of tokens the dispatch emitted across the pool."""
        with self._window_lock:
            w = self._window
            if w is None:
                return
            w["steps"] += 1
            w["tokens"] += int(tokens)
            if w["steps"] >= self.steps:
                self._close(w)

    def _deadline_close(self, window_id: int) -> None:
        """Watchdog fire: close the window if it is STILL the open
        one (a normal boundary close cancels the timer, but a fire
        racing the cancel must not close the next window)."""
        with self._window_lock:
            w = self._window
            if w is None or w["window"] != window_id:
                return
            w["deadline_closed"] = True
            self._close(w)

    def _close(self, w: Dict[str, Any]) -> None:
        """Window boundary reached (``_window_lock`` held): hand the
        WHOLE close — profiler stop, dump export, parse — to a
        background thread.  The engine thread pays a thread spawn,
        nothing else; the trace keeps collecting a few extra
        milliseconds until the analyzer thread stops it, which is
        harmless because the parser anchors attribution to the
        window's own ``ptpu_step`` markers (first ``steps`` of them
        — a dispatch racing the async stop can land an EXTRA marker
        in the dump) — the window is exact however late the stop
        lands.  The profiler session stays owned ("recorder") until
        that stop completes, so a racing manual /profile/start still
        sees single-flight truth."""
        self._window = None
        t = w.pop("_timer", None)
        if t is not None:
            t.cancel()
        w["host_wall_s"] = round(time.perf_counter() - w["t0"], 6)
        del w["t0"]
        w["mean_position"] = round(w.pop("pos_sum")
                                   / max(1, w.pop("pos_n")), 1)
        if self.tel is not None:
            self.tel.instant(0, "profile_window_stop",
                             time.perf_counter(),
                             pid=ENGINE_PID, id=w["window"],
                             steps=w["steps"], tokens=w["tokens"])
        t = threading.Thread(target=self._finish, args=(w,),
                             name="flight-recorder", daemon=True)
        self._analyzer = t
        t.start()

    # -- background stop + analysis -------------------------------------

    def _finish(self, w: Dict[str, Any]) -> None:
        try:
            self.session.stop(owner="recorder")
        except Exception as e:
            # ANY stop failure (owner race, but also OSError from the
            # dump export on a full disk) must be recorded, never
            # allowed to kill the analyzer thread silently.
            with self._lock:
                self.last_error = f"stop window {w['window']}: " \
                                  f"{type(e).__name__}: {e}"
            self._retain_failed_dump(w["trace_dir"])
            return
        self._analyze(w)

    def _analyze(self, w: Dict[str, Any]) -> None:
        try:
            from ..analysis.xprof import attribute_dump

            # max_steps: anchor to the window's OWN markers — the
            # async stop can let the next dispatch land one more
            # ptpu_step in the dump, which would stretch wall_s over
            # steps the tokens/steps counters never saw.
            att = attribute_dump(w["trace_dir"],
                                 max_steps=w["steps"] or None)
            rec = self._build_record(w, att)
        except Exception as e:
            with self._lock:
                self.last_error = \
                    f"analyze window {w['window']}: " \
                    f"{type(e).__name__}: {e}"
            self._retain_failed_dump(w["trace_dir"])
            return
        self._discard_dump(w["trace_dir"])
        with self._lock:
            self._latest = rec
            self._windows.append(rec)
            self.windows_analyzed += 1
            self.last_error = None

    @staticmethod
    def _discard_dump(path: str) -> None:
        """Recorder dumps are read ONCE by the parser, then deleted:
        a production recorder opens a window every few seconds of
        traffic and each xprof session is MBs, so without retention
        ``--profile-dir`` grows without bound.  Manual
        ``/profile/start`` dumps live in their own session dirs and
        are never touched."""
        import shutil

        shutil.rmtree(path, ignore_errors=True)

    def _retain_failed_dump(self, path: str) -> None:
        """Keep exactly ONE failed dump (the newest) for debugging a
        parse error — a PERSISTENT failure must not re-grow the
        disk either."""
        with self._lock:
            prev, self._failed_dump = self._failed_dump, path
        if prev is not None and prev != path:
            self._discard_dump(prev)

    def _build_record(self, w: Dict[str, Any],
                      att: Dict[str, Any]) -> Dict[str, Any]:
        """One attribution record = the /profile/report body = the
        /metrics gauge source.  The parser's trace-internal wall is
        the denominator everywhere (host_wall_s rides along for
        comparison)."""
        rec = {**w, **att, "completed_at": time.time(),
               "collective_share": att["shares"]["collective"],
               "transfer_share": att["shares"]["transfer"],
               "compute_share": att["shares"]["compute"]}
        mfu = None
        fpt = None
        if self.flops_fn is not None and w["tokens"] > 0 \
                and att["wall_s"] > 0:
            fpt = self.flops_fn(w.get("mean_position") or 0.0)
            if fpt:
                mfu = (w["tokens"] * fpt
                       / (att["wall_s"] * self.peak_flops
                          * self.n_devices))
        rec["flops_per_token"] = round(fpt, 1) if fpt else None
        rec["mfu"] = round(mfu, 6) if mfu is not None else None
        rec["peak_flops"] = self.peak_flops
        rec["peak_flops_source"] = self.peak_flops_source
        rec["n_devices"] = self.n_devices
        return rec

    # -- read side ------------------------------------------------------

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._latest

    def report(self) -> Dict[str, Any]:
        """The ``GET /profile/report`` body: the latest record plus
        the bounded window history (oldest first) — trace_report.py
        renders its host-gap strip from ``windows``."""
        with self._lock:
            return {"every": self.every, "steps": self.steps,
                    "windows_total": self.windows_total,
                    "windows_analyzed": self.windows_analyzed,
                    "windows_skipped": self.windows_skipped,
                    "windows_deferred": self.windows_deferred,
                    "last_error": self.last_error,
                    "latest": self._latest,
                    "windows": list(self._windows)}

    def info_block(self) -> Dict[str, Any]:
        """The ``/info`` ``profiling`` block — the same published
        record, summarized."""
        with self._lock:
            latest, err = self._latest, self.last_error
            block: Dict[str, Any] = {
                "enabled": True, "every": self.every,
                "steps": self.steps,
                "windows_total": self.windows_total,
                "windows_analyzed": self.windows_analyzed,
                "windows_skipped": self.windows_skipped,
                "windows_deferred": self.windows_deferred,
            }
        if err:
            block["last_error"] = err
        if latest is not None:
            block.update(
                last_window=latest["window"],
                last_window_age_s=round(
                    time.time() - latest["completed_at"], 1),
                category_seconds={**latest["category_s"],
                                  "host_gap": latest["host_gap_s"]},
                collective_share=latest["collective_share"],
                host_gap_share=latest["host_gap_share"],
                device_busy_share=latest["device_busy_share"],
                mfu=latest["mfu"],
                host_fallback=latest["host_fallback"])
        return block

    def metrics_lines(self) -> List[str]:
        """Prometheus exposition for the attribution gauges —
        rendered from the SAME record /profile/report returns (one
        reduction, no drift).  The share gauges appear once the first
        window has been analyzed; the window counters are always
        present."""
        with self._lock:
            latest = self._latest
            lines = [
                # Same semantics as /info + /profile/report under
                # the same names: _total counts windows OPENED,
                # _analyzed_total records PUBLISHED (an analysis
                # failure moves one, not the other).
                "# TYPE ptpu_serving_profile_windows_total counter",
                f"ptpu_serving_profile_windows_total "
                f"{self.windows_total}",
                "# TYPE ptpu_serving_profile_windows_analyzed_total "
                "counter",
                f"ptpu_serving_profile_windows_analyzed_total "
                f"{self.windows_analyzed}",
                "# TYPE ptpu_serving_profile_windows_skipped_total "
                "counter",
                f"ptpu_serving_profile_windows_skipped_total "
                f"{self.windows_skipped}",
                "# TYPE ptpu_serving_profile_windows_deferred_total "
                "counter",
                f"ptpu_serving_profile_windows_deferred_total "
                f"{self.windows_deferred}",
            ]
        if latest is not None:
            lines += [
                "# TYPE ptpu_serving_collective_share gauge",
                f"ptpu_serving_collective_share "
                f"{latest['collective_share']}",
                "# TYPE ptpu_serving_host_gap_share gauge",
                f"ptpu_serving_host_gap_share "
                f"{latest['host_gap_share']}",
                "# TYPE ptpu_serving_device_busy_share gauge",
                f"ptpu_serving_device_busy_share "
                f"{latest['device_busy_share']}",
            ]
            if latest["mfu"] is not None:
                lines += [
                    "# TYPE ptpu_serving_mfu gauge",
                    f"ptpu_serving_mfu {latest['mfu']}",
                ]
        return lines

    def close(self, timeout: float = 10.0) -> None:
        """End-of-life: abandon an open window (the owning
        ProfileSession.close stops the trace) and wait briefly for a
        running analyzer so test teardown never leaks threads."""
        with self._window_lock:
            w, self._window = self._window, None
            if w is not None:
                t = w.pop("_timer", None)
                if t is not None:
                    t.cancel()
        if w is not None:
            try:
                self.session.stop(owner="recorder")
            except Exception:
                # Best-effort teardown: the owning ProfileSession's
                # close() also force-stops whatever is left.
                import logging

                logging.getLogger(__name__).debug(
                    "recorder window stop at close failed",
                    exc_info=True)
        t = self._analyzer
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
