"""Request-scoped debuggability: IDs, causal timelines, live engine
introspection, and the stall watchdog.

The telemetry layer (telemetry.py) and the flight recorder
(profiling.py) answer AGGREGATE questions — histograms, trace rings,
device-time shares.  A production incident asks two different ones:
"what happened to THIS request" and "why is the engine making no
progress right now".  This module is that layer:

- **Request IDs** — every request gets one (the server honors an
  inbound ``X-Request-Id``, else :func:`new_request_id` makes one),
  echoed on EVERY response (success and 4xx/5xx), stamped into the
  access log, every trace-ring span the request emits, the
  ``timings`` block, and the request-history record below.  The ID is
  the correlation key the future multi-replica router tier
  (ROADMAP 3) needs to exist BEFORE it can be debugged.

- :class:`RequestHistory` — a bounded retention ring of terminal
  (completed/failed/shed/cancelled/expired) request records, separate
  from the event trace ring and with its own capacity knob
  (``--request-history``).  Each record is the request's CAUSAL
  timeline: queue wait by class, the admission slot, per-chunk
  prefill, every preemption with the PREEMPTOR's request ID and the
  control-law reason, page-block waits and what unblocked them,
  prefix-cache hit provenance, spec acceptance, and the terminal
  cause.  Served by ``GET /requests/<id>`` and ``GET /requests``.

- :class:`SnapshotBoard` — the ``GET /debug/state`` consistency
  mechanism: the engine builds a host-side snapshot of its internals
  at each step BOUNDARY (slot table, per-class queues with entry
  ages, page pool, lifecycle flags) and publishes it here under
  ``_state_lock``; handlers serve the latest published snapshot plus
  its age.  The contract (docs/DESIGN.md): snapshot construction and
  serving NEVER acquire the device lock — machine-checked by the
  SNAPSHOT-LOCK rule (analysis/rules.py).

- :class:`StallWatchdog` — a monitor thread that declares a STALL
  when work exists (residents or queued streams) but no step boundary
  completes for ``--stall-timeout`` seconds, or a queued request's
  age exceeds ``queue_factor`` times its class queue deadline.  On
  the first detection of an episode it writes a one-shot DIAGNOSTIC
  BUNDLE to disk — forced state snapshot + the trace ring's tail +
  every thread's Python stack (:func:`dump_thread_stacks`) — and
  bumps ``ptpu_serving_stalls_total``: the artifact that turns
  "engine wedged, restart and lose the evidence" into a bug report
  attachment.  It re-arms itself once boundaries resume.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["new_request_id", "sanitize_request_id",
           "format_replica_rid", "parse_replica_rid",
           "RequestHistory", "SnapshotBoard", "StallWatchdog",
           "dump_thread_stacks", "events_to_dicts"]

# Inbound X-Request-Id values are used as log fields, JSON keys, and
# file-name-adjacent strings — constrain them to a sane charset and
# length; anything else gets a generated ID instead (a malformed
# header must not break correlation for everyone else).
_RID_RE = re.compile(r"^[A-Za-z0-9._:\-]{1,128}$")


def new_request_id() -> str:
    """A fresh request ID: 16 hex chars of uuid4 — short enough for
    log lines, collision-safe at any single-replica rate (and the
    router tier will prefix replica IDs, not rely on global
    uniqueness)."""
    return uuid.uuid4().hex[:16]


def sanitize_request_id(raw: Optional[str]) -> Optional[str]:
    """The inbound ``X-Request-Id`` if it is usable, else None (the
    caller generates).  Never raises: a hostile header downgrades to
    a generated ID, not a 500."""
    if not raw or not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _RID_RE.match(raw) else None


# The replica-id prefix the router stamps on forwarded request IDs:
# ``r<N>-<rid>``.  One parse/format pair here instead of string
# splicing at the call sites — the router's forwarding headers, the
# /fleet/requests stitcher, and trace_report.py all have to agree on
# this convention or cross-tier correlation silently breaks.
_REPLICA_RID_RE = re.compile(r"^(r\d+)-(.+)$")


def format_replica_rid(replica_id: str, rid: str) -> str:
    """The request ID forwarded REPLICA-ward for one (request,
    replica) leg: ``r0-<rid>``, length-capped to the same 128-char
    bound :data:`_RID_RE` enforces inbound (a router must never mint
    an ID a replica would reject and regenerate — that breaks the
    correlation the prefix exists for)."""
    return f"{replica_id}-{rid}"[:128]


def parse_replica_rid(prefixed: str):
    """``(replica_id, rid)`` for a router-prefixed request ID, or
    ``(None, prefixed)`` when the ID carries no replica prefix (a
    request that reached the replica directly).  The inverse of
    :func:`format_replica_rid` for well-formed prefixes; never
    raises."""
    if not isinstance(prefixed, str):
        return None, prefixed
    m = _REPLICA_RID_RE.match(prefixed)
    if m is None:
        return None, prefixed
    return m.group(1), m.group(2)


def events_to_dicts(events, t0: float) -> List[Dict[str, Any]]:
    """Render (name, t_start, t_end, args) span tuples as record
    entries: start/duration in ms relative to request submission —
    the same shape as the response ``timings`` block, so a record's
    timeline and a live ``timings`` response read identically."""
    out = []
    for name, a, b, args in events:
        ev = {"name": name,
              "start_ms": round(1e3 * (a - t0), 3),
              "dur_ms": round(1e3 * (b - a), 3)}
        if args:
            ev["args"] = args
        out.append(ev)
    return out


class RequestHistory:
    """Bounded ring of terminal request records, keyed by request ID.

    ``record`` REPLACES an existing record with the same ID (the
    engine's full causal record supersedes a front-end give-up's
    minimal one; a client reusing an ID sees its latest request).
    All methods are thread-safe; records are plain JSON-able dicts.
    ``capacity == 0`` disables recording entirely — ``record`` is one
    attribute check, the same off-switch contract as the trace ring.
    """

    def __init__(self, capacity: int = 256):
        capacity = int(capacity)
        if capacity < 0:
            raise ValueError(
                f"request_history must be >= 0; got {capacity}")
        self.enabled = capacity > 0
        self.capacity = capacity
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self.recorded_total = 0
        self.evicted_total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def record(self, rec: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        rid = rec.get("request_id")
        with self._lock:
            if rid is not None:
                for i, old in enumerate(self._ring):
                    if old.get("request_id") == rid:
                        del self._ring[i]
                        break
            if len(self._ring) == self._ring.maxlen:
                self.evicted_total += 1
            self._ring.append(rec)
            self.recorded_total += 1

    def record_front(self, rec: Dict[str, Any]) -> None:
        """Insert a FRONT-END record only when no record exists for
        this ID yet: the engine's full causal record must never be
        clobbered by the handler's minimal status line (the reverse —
        a later engine record replacing a minimal front-end one via
        :meth:`record` — is the intended supersede)."""
        if not self.enabled:
            return
        rid = rec.get("request_id")
        # Check and insert under ONE lock hold: releasing between the
        # existence check and a record() call would let an engine
        # record land in the gap and be clobbered by this minimal one
        # (engine terminal paths wake the waiter BEFORE recording, so
        # the handler genuinely races us here).
        with self._lock:
            if rid is not None and any(
                    old.get("request_id") == rid
                    for old in self._ring):
                return
            if len(self._ring) == self._ring.maxlen:
                self.evicted_total += 1
            self._ring.append(rec)
            self.recorded_total += 1

    def get(self, rid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("request_id") == rid:
                    return dict(rec)
        return None

    def list(self, status: Optional[str] = None,
             limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-first summaries (the full record stays behind
        ``GET /requests/<id>`` — a list response must stay small)."""
        out = []
        if limit <= 0:
            return out
        with self._lock:
            records = list(self._ring)
        for rec in reversed(records):
            if status is not None and rec.get("status") != status:
                continue
            out.append({k: rec.get(k) for k in (
                "request_id", "status", "kind", "priority", "rows",
                "path", "wall_s", "queue_wait_s", "ttft_s",
                "preempts", "resumes", "error", "t")
                if k in rec})
            if len(out) >= limit:
                break
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"request_history": self.capacity,
                    "request_records": len(self._ring),
                    "request_records_total": self.recorded_total,
                    "request_records_evicted": self.evicted_total}


class SnapshotBoard:
    """The published engine-state snapshot behind ``GET /debug/state``.

    The engine BUILDS a snapshot at each step boundary (on its own
    thread, outside the device lock) and publishes it here; readers
    get the latest copy plus its age.  ``_state_lock`` guards only
    the reference swap/copy — by the SNAPSHOT-LOCK contract nothing
    under it may acquire the device lock, so a wedged device call can
    never make ``/debug/state`` hang."""

    def __init__(self):
        self._state_lock = threading.Lock()
        self._snapshot: Optional[Dict[str, Any]] = None

    def publish(self, snap: Dict[str, Any]) -> None:
        with self._state_lock:
            self._snapshot = snap

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._state_lock:
            snap = self._snapshot
            return dict(snap) if snap is not None else None


def dump_thread_stacks() -> Dict[str, List[str]]:
    """Every live thread's Python stack, faulthandler-style but
    JSON-able: ``{"<thread name>:<ident>": [frame lines...]}``.  Pure
    stdlib introspection — safe to call from the watchdog while the
    engine thread is wedged inside a device call (the wedged frame is
    exactly the evidence the bundle exists to capture)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')}:{tid}"
        out[label] = [ln.rstrip("\n") for ln in
                      traceback.format_stack(frame)]
    return out


class StallWatchdog(threading.Thread):
    """Declare engine stalls and dump the evidence before a restart
    destroys it.

    Stall condition (checked every ``poll_s``): work exists —
    resident slots or queued streams — and

    - no step boundary completed for ``timeout_s``
      (``engine.last_boundary_t`` stale: the host-bound / wedged-
      device signature of arXiv:2011.03641), or
    - a queued stream's age exceeds ``queue_factor`` x its class
      queue deadline (the sweep should have shed it long ago — if it
      is still queued, the sweep itself is not running).

    First detection of an episode writes ONE diagnostic bundle
    (``stall_<n>.json`` under ``out_dir``): stall metadata, a FORCED
    state snapshot (built on this thread — best effort, labeled
    ``forced``), the last ``trace_tail`` trace events, and every
    thread's stack.  The episode re-arms when a boundary completes
    after the firing, so a recovered engine that stalls again gets a
    fresh bundle.  The watchdog never touches the device lock and
    never raises out of its loop."""

    def __init__(self, engine, telemetry, *, timeout_s: float,
                 out_dir: str = ".", queue_factor: float = 4.0,
                 trace_tail: int = 512,
                 poll_s: Optional[float] = None,
                 extra_state=None):
        if timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0; got {timeout_s}")
        super().__init__(name="stall-watchdog", daemon=True)
        self.engine = engine
        self.telemetry = telemetry
        self.timeout_s = float(timeout_s)
        self.out_dir = out_dir
        self.queue_factor = float(queue_factor)
        self.trace_tail = int(trace_tail)
        self.poll_s = poll_s if poll_s is not None \
            else max(0.02, self.timeout_s / 4.0)
        # Server-level state (draining flag, history stats, sanitizer
        # graph) folded into the bundle's snapshot: a zero-arg
        # callable so the watchdog needs no back-reference to the
        # server.
        self.extra_state = extra_state
        self.stalls_total = 0
        self.last_stall: Optional[Dict[str, Any]] = None
        # NOT ``_stop``: Thread.join() calls its private _stop()
        # internally, and shadowing it with an Event breaks join.
        self._stopped = threading.Event()
        # Armed = no bundle fired for the CURRENT episode; an episode
        # ends (and re-arms the next) when last_boundary_t advances
        # past the boundary observed at firing time.
        self._fired_boundary: Optional[float] = None
        # queue_age episodes are keyed per REQUEST, not per boundary:
        # a healthy-stepping engine advances the boundary every tick,
        # which would re-arm and re-fire the same ancient request on
        # every poll — one bundle per offending rid instead.
        self._fired_queue_rids: set = set()

    def close(self) -> None:
        self._stopped.set()

    def run(self) -> None:
        while not self._stopped.wait(self.poll_s):
            try:
                self.check()
            except Exception:
                # The watchdog is last-resort diagnostics: it must
                # never take the server down, but a broken check
                # should be visible in debug logs.
                import logging

                logging.getLogger(__name__).debug(
                    "stall watchdog check failed", exc_info=True)

    # -- detection -------------------------------------------------------

    def check(self) -> Optional[str]:
        """One detection pass; returns the bundle path when a stall
        fired (tests drive this directly, without the thread)."""
        eng = self.engine
        boundary = eng.last_boundary_t
        if self._fired_boundary is not None:
            if boundary > self._fired_boundary:
                self._fired_boundary = None     # progress: re-arm
            else:
                return None                     # one-shot per episode
        now = time.perf_counter()
        stale_s = now - boundary
        busy = bool(eng._resident) or len(eng.queue) > 0
        reason = None
        detail: Dict[str, Any] = {}
        if busy and stale_s > self.timeout_s:
            reason = "no_step_boundary"
            detail = {"stale_s": round(stale_s, 3),
                      "timeout_s": self.timeout_s}
        else:
            pol = eng.policy
            if pol.queue_deadline_s is not None \
                    or pol.batch_queue_deadline_s is not None:
                queued_rids = set()
                for s in eng.queue.snapshot():
                    queued_rids.add(s.group.rid)
                    qd = pol.class_queue_deadline(s.group.priority)
                    if qd is None:
                        continue
                    age = now - s.group.t_submit
                    if age > self.queue_factor * qd \
                            and s.group.rid \
                            not in self._fired_queue_rids:
                        reason = "queue_age"
                        detail = {
                            "request_id": s.group.rid,
                            "priority": s.group.priority,
                            "age_s": round(age, 3),
                            "class_deadline_s": qd,
                            "factor": self.queue_factor}
                        self._fired_queue_rids.add(s.group.rid)
                        break
                else:
                    # Complete scan, nothing fired: drop fired rids
                    # that left the queue, so the set stays bounded
                    # by queue depth (a partial scan after a fire
                    # must not prune rids it never reached).
                    self._fired_queue_rids &= queued_rids
        if reason is None:
            return None
        return self._fire(reason, detail, boundary)

    # -- the bundle ------------------------------------------------------

    def _fire(self, reason: str, detail: Dict[str, Any],
              boundary: float) -> Optional[str]:
        self._fired_boundary = boundary
        self.stalls_total += 1
        stall = {"reason": reason, **detail,
                 "t": round(time.time(), 3),
                 "stalls_total": self.stalls_total}
        self.last_stall = stall
        bundle = self.build_bundle(stall)
        path = None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"stall_{self.stalls_total}_{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1, default=str)
        except Exception:
            # A read-only disk must not kill the watchdog — the
            # in-memory last_stall and the counter still tell the
            # operator a stall happened.
            import logging

            logging.getLogger(__name__).warning(
                "stall bundle write failed (stall still counted)",
                exc_info=True)
        stall["bundle"] = path
        if self.telemetry is not None:
            from .telemetry import ENGINE_PID

            self.telemetry.instant(
                0, "stall", time.perf_counter(), pid=ENGINE_PID,
                reason=reason, **({"bundle": path} if path else {}))
        print(f"# serving: STALL detected ({reason}) — diagnostic "
              f"bundle: {path or 'WRITE FAILED'}", file=sys.stderr)
        return path

    def build_bundle(self, stall: Dict[str, Any]) -> Dict[str, Any]:
        """The diagnostic bundle dict (also the loadable on-disk
        shape).  Built entirely host-side: forced snapshot, trace
        tail, thread stacks — never the device lock."""
        try:
            state = self.engine.build_debug_snapshot(forced=True)
        except Exception as e:
            # A wedged engine's host structures can be mid-mutation;
            # a partial bundle beats none.
            state = {"error": f"{type(e).__name__}: {e}"}
        if self.extra_state is not None:
            try:
                state["server"] = self.extra_state()
            except Exception as e:
                state["server"] = {
                    "error": f"{type(e).__name__}: {e}"}
        events = []
        if self.telemetry is not None:
            events = self.telemetry.events()[-self.trace_tail:]
        return {"stall": stall,
                "state": state,
                "trace_tail": events,
                "threads": dump_thread_stacks()}

    def status(self) -> Dict[str, Any]:
        return {"armed": True, "timeout_s": self.timeout_s,
                "queue_factor": self.queue_factor,
                "dir": self.out_dir,
                "stalls_total": self.stalls_total,
                **({"last_stall": self.last_stall}
                   if self.last_stall is not None else {})}
