"""Mesh placement for the serving engine — the EXACT tensor-parallel
serving layout.

The decode engine is mesh-native when the server passes a
``ServingMesh``: model params go under ``NamedSharding`` over a
``jax.sharding.Mesh`` built from the seed's ``parallel.mesh.MeshSpec``
machinery, and the slot-stacked KV cache (fixed-lane pool, paged page
pool, and the draft pools) shards its HEADS axis over ``tp`` — the
memory that actually scales with slots x context, and the bandwidth
the decode step streams every token.

Layout contract — REDUCTION-FREE by construction, so meshed serving
is TOKEN-BITWISE-IDENTICAL to the unmeshed engine per seed (the
repo's determinism backbone extends to every mesh shape instead of
degrading to "numerically close"):

- COLUMN-PARALLEL params shard their OUTPUT dim over ``tp``
  (q/k/v/qkv projections, gate/up/fc1 MLP inputs): each device
  computes its own output columns over the FULL contraction dim, so
  every output element keeps the exact accumulation order of the
  unmeshed matmul.
- The KV cache shards over HEADS: per-head attention (scores,
  softmax, values) touches only that head's data — no cross-device
  math at all.
- ROW-PARALLEL weights (o_proj/down_proj/fc2), embeddings, norms and
  the lm_head stay REPLICATED, and the models' existing ``constrain``
  sites force their inputs replicated under the serving-exact mesh
  (``parallel.constraints.exact_mesh``): the all-gather that replaces
  Megatron's psum is a concatenation — bytes move, sums never
  reassociate.  (True row-parallel weight sharding for over-chip
  params needs an approximate-equality contract and is the ROADMAP
  residual, with multi-host meshes.)
- MoE expert params ([E, in, out]) shard the EXPERT dim over ``ep``:
  decode's per-token expert gather fetches the routed expert's
  weights cross-device, per-expert math untouched.
- The slot axis is replicated by default, or data-parallel over
  ``dp`` (fixed-lane pools only): each device steps its own slots
  with replicated weights.

Divisibility of what the mesh CLAIMS to shard is a STARTUP error,
not a silent replicate: a model whose KV head count doesn't divide
``tp`` (or expert count ``ep``, or slot count ``dp``) refuses to
serve meshed with a message naming the offending pair — KV/attention
sharding is the win the mesh advertises, and degrading it silently
to replication would report mesh wins that don't exist.  The one
deliberate replicate-fallback is a COLUMN-PARALLEL MLP kernel whose
output dim happens not to divide ``tp`` (e.g. an odd
``intermediate_size``): that weight stays replicated — already the
row-parallel weights' placement, bitwise-identical either way — and
the KV/attention sharding the startup checks guarantee is
unaffected.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..parallel import constraints as _constraints
from ..parallel.mesh import MeshError, MeshSpec, build_mesh

__all__ = ["ServingMesh", "parse_mesh", "MeshError"]

# Axes the serving engine speaks.  fsdp/pp/sp are training-stack
# strategies (gradient sharding, stage pipelining) with no serving
# semantics here — requesting them is a usage error, not a no-op.
SERVING_AXES = ("dp", "tp", "ep")

# Column-parallel kernels: output dim sharded, contraction dim whole
# — the reduction-free subset of parallel.strategies.TP_RULES.  Row-
# parallel names (o_proj/down_proj/fc2/wo) are deliberately ABSENT:
# sharding their input dim makes XLA psum partial products, which
# reorders float accumulation and breaks the bitwise contract.
_COL_PARALLEL = re.compile(
    r"(q_proj|k_proj|v_proj|qkv|query|key|value"
    r"|fc1|wi|up_proj|gate_proj|intermediate)[^/]*/kernel")
_EP_PARALLEL = re.compile(r"experts_w[12]$")

# Cache-collection leaves that carry a HEADS axis at ndim-2 (the
# [..., B, positions, heads, feat] layout of kv_cache.append_kv_cache
# and the int8 scale leaves; stacked/paged pools only ADD leading or
# split middle axes, so heads stays at ndim-2 in every storage
# discipline).
_KV_LEAVES = ("cached_key", "cached_value", "cached_key_scale",
              "cached_value_scale")


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None) or getattr(p, "name", None) or \
            getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts)


def parse_mesh(arg) -> MeshSpec:
    """``"tp=4"`` / ``"tp=2,ep=2"`` / dict / MeshSpec -> a serving
    MeshSpec (absent axes default to 1 — never -1 fill: a serving
    mesh uses exactly the devices it asks for)."""
    if isinstance(arg, MeshSpec):
        spec = arg
    else:
        if isinstance(arg, str):
            sizes: Dict[str, int] = {}
            for part in arg.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise MeshError(
                        f"mesh axis {part!r} must be AXIS=SIZE "
                        f"(e.g. tp=4)")
                k, _, v = part.partition("=")
                try:
                    sizes[k.strip()] = int(v)
                except ValueError:
                    raise MeshError(
                        f"mesh axis size {v!r} is not an integer")
        elif isinstance(arg, dict):
            try:
                sizes = {k: int(v) for k, v in arg.items()}
            except (TypeError, ValueError):
                raise MeshError(
                    f"mesh axis sizes must be integers; got {arg!r}")
        else:
            raise MeshError(
                f"mesh must be a spec string (tp=4), a dict, or a "
                f"MeshSpec; got {type(arg).__name__}")
        unknown = set(sizes) - set(SERVING_AXES)
        if unknown:
            raise MeshError(
                f"serving mesh supports axes {SERVING_AXES}; got "
                f"{sorted(unknown)} (fsdp/pp/sp are training "
                f"strategies)")
        # Absent axes default to 1 (never MeshSpec's -1 fill: a
        # serving mesh uses exactly the devices it asks for).
        for axis in SERVING_AXES:
            sizes.setdefault(axis, 1)
        spec = MeshSpec.from_dict(sizes)
    for axis in ("fsdp", "pp", "sp"):
        if getattr(spec, axis) not in (1,):
            raise MeshError(
                f"serving mesh supports axes {SERVING_AXES}; "
                f"{axis}={getattr(spec, axis)} is a training "
                f"strategy")
    for axis in SERVING_AXES:
        size = getattr(spec, axis)
        if size == -1:
            raise MeshError(
                f"serving mesh sizes must be explicit; {axis}=-1 "
                f"(fill) is a training-spec convention")
        if size < 1:
            raise MeshError(f"mesh axis {axis} must be >= 1; got "
                            f"{size}")
    return spec


class ServingMesh:
    """One mesh + the serving placement rules over it.

    Built once at server startup over the FIRST ``dp * tp * ep``
    local devices; every placement below commits arrays to
    ``NamedSharding``s of this mesh (replication included — an
    uncommitted array fed to a mesh program forces a per-call
    transfer, the SHARD-LEAK class ``ptpu check`` flags)."""

    def __init__(self, spec, devices: Optional[Sequence] = None):
        import jax

        self.spec = parse_mesh(spec)
        self.dp = self.spec.dp
        self.tp = self.spec.tp
        self.ep = self.spec.ep
        self.n_devices = self.dp * self.tp * self.ep
        if devices is None:
            devices = jax.devices()
        if len(devices) < self.n_devices:
            raise MeshError(
                f"mesh {self.describe()['axes']} needs "
                f"{self.n_devices} devices; only {len(devices)} "
                f"available (on CPU, set XLA_FLAGS="
                f"--xla_force_host_platform_device_count=N)")
        self.mesh = build_mesh(self.spec,
                               devices=list(devices)[:self.n_devices])

    # -- introspection ---------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """The /info `mesh` block: active axes, sizes, device count."""
        return {
            "axes": {a: getattr(self, a) for a in SERVING_AXES
                     if getattr(self, a) > 1} or {"tp": 1},
            "devices": self.n_devices,
            "layout": "exact",
        }

    def axes_str(self) -> str:
        return ",".join(f"{a}={getattr(self, a)}"
                        for a in SERVING_AXES
                        if getattr(self, a) > 1) or "tp=1"

    # -- trace context ---------------------------------------------------

    def exact(self):
        """Context manager publishing the serving-exact mesh for jit
        traces inside it (parallel.constraints.exact_mesh)."""
        return _constraints.exact_mesh(self.mesh)

    # -- shardings -------------------------------------------------------

    @property
    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def _spec_sharding(self, *entries):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(*entries))

    # -- model validation ------------------------------------------------

    def validate_model(self, model, role: str = "model",
                       n_slots: Optional[int] = None) -> None:
        """Startup divisibility checks, with clean errors naming the
        offending (count, axis-size) pair."""
        cfg = getattr(model, "cfg", None)
        if self.tp > 1:
            heads = getattr(cfg, "num_kv_heads", None)
            label = "num_kv_heads"
            if heads is None:
                heads = getattr(cfg, "num_heads", None)
                label = "num_heads"
            if heads is None:
                raise MeshError(
                    f"mesh tp={self.tp}: the {role} has no head "
                    f"count (cfg.num_heads) to shard the KV cache "
                    f"over")
            if heads % self.tp:
                raise MeshError(
                    f"the {role}'s KV head count ({label}={heads}) "
                    f"is not divisible by mesh tp={self.tp}; pick a "
                    f"tp that divides it (sharding that silently "
                    f"replicates would fake the mesh win)")
        if self.ep > 1:
            experts = getattr(cfg, "num_experts", None)
            if experts is None:
                raise MeshError(
                    f"mesh ep={self.ep}: the {role} has no experts "
                    f"(cfg.num_experts) to shard")
            if experts % self.ep:
                raise MeshError(
                    f"the {role}'s expert count ({experts}) is not "
                    f"divisible by mesh ep={self.ep}")
        if self.dp > 1 and n_slots is not None and n_slots % self.dp:
            raise MeshError(
                f"n_slots ({n_slots}) is not divisible by mesh "
                f"dp={self.dp} (dp shards the slot axis)")

    # -- param placement -------------------------------------------------

    def param_shardings(self, variables) -> Any:
        """NamedSharding pytree for ``variables``: column-parallel
        kernels over tp, expert params over ep, everything else
        replicated (committed).  A column kernel whose output dim
        doesn't divide tp replicates (see the module docstring: the
        attention/KV dims are guaranteed divisible by
        validate_model; MLP widths are best-effort)."""
        import jax

        def leaf_sharding(path, leaf):
            name = _path_str(path)
            shape = getattr(leaf, "shape", ())
            nd = len(shape)
            if self.ep > 1 and _EP_PARALLEL.search(name) and nd >= 1 \
                    and shape[0] % self.ep == 0:
                return self._spec_sharding(
                    *(["ep"] + [None] * (nd - 1)))
            if self.tp > 1 and _COL_PARALLEL.search(name) \
                    and nd >= 2 and shape[-1] % self.tp == 0:
                return self._spec_sharding(
                    *([None] * (nd - 1) + ["tp"]))
            return self.replicated

        return jax.tree_util.tree_map_with_path(leaf_sharding,
                                                variables)

    def place_params(self, variables) -> Any:
        import jax

        shardings = self.param_shardings(variables)
        return jax.tree_util.tree_map(jax.device_put, variables,
                                      shardings)

    # -- KV cache placement ----------------------------------------------

    def cache_leaf_sharding(self, key: str, leaf, *,
                            slot_axis: bool = False):
        """NamedSharding for one cache-collection leaf (by tree-path
        ``key``): heads (ndim-2) over tp for the standard KV leaves,
        slot axis (0) over dp when the leaf belongs to a slot-stacked
        pool, everything else replicated."""
        shape = getattr(leaf, "shape", ())
        nd = len(shape)
        spec = [None] * nd
        named = any(key.endswith(f"{n}']") or key.endswith(n)
                    for n in _KV_LEAVES)
        if self.tp > 1 and named and nd >= 2 \
                and shape[nd - 2] % self.tp == 0:
            spec[nd - 2] = "tp"
        if self.dp > 1 and slot_axis and nd >= 1 \
                and shape[0] % self.dp == 0:
            spec[0] = "dp"
        return self._spec_sharding(*spec)

    def cache_shardings(self, tree, *, slot_axis: bool = False):
        """NamedSharding pytree for a cache pytree (a B=1 template,
        or a slot-stacked pool when ``slot_axis``)."""
        import jax

        def leaf_sharding(path, leaf):
            return self.cache_leaf_sharding(
                jax.tree_util.keystr(path), leaf,
                slot_axis=slot_axis)

        return jax.tree_util.tree_map_with_path(leaf_sharding, tree)

    def place_cache(self, tree, *, slot_axis: bool = False):
        import jax

        return jax.tree_util.tree_map(
            jax.device_put, tree,
            self.cache_shardings(tree, slot_axis=slot_axis))

    # -- paged pool placement --------------------------------------------

    def pool_leaf_sharding(self, meta: Dict[str, Any], pool_leaf):
        """NamedSharding for one PAGED pool leaf.  The pool splits the
        position axis into (n_pages, page_tokens), shifting heads to
        ``pos_axis + 2`` == pool ndim-2 for the named KV layout;
        unnamed fallback leaves (unknown head position) replicate."""
        nd = getattr(pool_leaf, "ndim", 0)
        spec = [None] * nd
        if self.tp > 1 and meta.get("heads_axis") is not None:
            axis = meta["heads_axis"]
            if axis < nd and pool_leaf.shape[axis] % self.tp == 0:
                spec[axis] = "tp"
        return self._spec_sharding(*spec)

    # -- host-array placement --------------------------------------------

    def put_replicated(self, x):
        """Commit a host array to the mesh, replicated — the
        sanctioned spelling for feeding host-built operands to a
        mesh-compiled program (SHARD-LEAK)."""
        import jax

        return jax.device_put(np.asarray(x), self.replicated)
