"""Scheduler policy for the continuous-batching engine.

The engine (engine.py) decouples the LOGICAL workload (a stream of
requests with arbitrary prompt lengths and token budgets) from the
PHYSICAL batch (a fixed pool of decode slots): requests wait in a
bounded admission queue, are prefilled chunk-by-chunk between decode
steps, and enter a slot at a decode-step boundary.  This module owns
the passive pieces of that design:

- :class:`RequestGroup` / :class:`Stream` — one /generate request and
  its per-row decode streams (a B-row request is B independent
  streams: decode rows never interact, so rows of one request need not
  occupy adjacent slots or even be resident together).
- :class:`SamplingSpec` — the per-request (seed, temperature, top_k,
  top_p) every stream carries into its slot; temperature 0 is greedy,
  and sampled streams draw under the position-keyed RNG contract
  (models/generate), so tokens never depend on the schedule.
- :class:`AdmissionQueue` — the bounded, PER-PRIORITY-CLASS FIFO
  between the HTTP front-end and the engine.  Submission is
  all-or-nothing per request; a full class queue raises
  :class:`QueueFullError`, which the front-end maps to 429 +
  Retry-After (explicit backpressure instead of an unbounded thread
  pile-up).  The engine pops class-aware: ``interactive`` ahead of
  ``batch`` — the "defer" half of preempt-or-defer.
- :class:`SchedulerPolicy` — the knobs: slot count, per-class queue
  depths and queue deadlines, the default prefill chunk, how much
  prefill work may run per decode boundary (1 chunk while decodes are
  active — prefill must never starve the running batch — bursting
  only when the batch is idle), and the interactive-TTFT SLO target
  that arms batch preemption.

REQUEST LIFECYCLE (the robustness layer): every request is a
first-class cancellable, deadline-bearing, prioritized object.  A
group carries an optional absolute ``deadline`` and a cancel request
(:meth:`RequestGroup.request_cancel`, set from any thread); the
engine DELIVERS both at step boundaries only — lifecycle control is
host-side scheduling, never part of a compiled step program (the
Podracer decoupled-dataflow split, arXiv:2104.06272; machine-checked
by the JIT-DEADLINE rule in analysis/rules.py).  Terminal statuses:

    queued -> prefill -> decoding -> complete
                 |           |-----> cancelled   (client went away)
                 |           |-----> expired     (deadline passed)
                 |           `-----> preempted -> requeued (resumes
                 |                   with its generated-so-far prefix)
                 `---------> shed    (cannot start before its class
                                      queue deadline, or draining)
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np


class SamplingSpec:
    """Per-request sampling parameters carried by every engine stream.

    ``temperature == 0`` is greedy (the default — top_k/top_p are
    inert then, matching solo ``generate``); ``top_k=0`` / ``top_p=0``
    encode "disabled" so the whole spec vmaps into the slot step
    program as plain numbers.  ``seed`` anchors the position-keyed
    RNG contract (models/generate.sample_stream_keys): row ``r``'s
    i-th generated token is drawn with
    ``fold_in(fold_in(PRNGKey(seed), r), i)`` — a function of (seed,
    row, token index) only, never of slot id, engine step count, or
    co-tenancy — which is what makes engine output independent of the
    admission schedule.

    ``spec_k > 0`` marks the request SPECULATIVE: its slots draft
    ``spec_k`` tokens per round from the engine's draft model and
    commit a variable accepted prefix (budget accounting stays in
    COMMITTED tokens — a stream is done when ``len(out)`` reaches its
    budget, however many rounds that took).  Speculative randomness
    is position-keyed too (per-(token index, lane) keys, see
    models/generate._spec_verify_row), so co-tenancy never changes a
    speculative response either.
    """

    __slots__ = ("seed", "temperature", "top_k", "top_p", "spec_k")

    def __init__(self, seed: int = 0, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 spec_k: int = 0):
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k) if top_k else 0
        self.top_p = float(top_p) if top_p else 0.0
        self.spec_k = int(spec_k) if spec_k else 0

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0

    @property
    def speculative(self) -> bool:
        return self.spec_k > 0

    def __repr__(self) -> str:  # debuggability in engine dumps
        return (f"SamplingSpec(seed={self.seed}, "
                f"temperature={self.temperature}, top_k={self.top_k}, "
                f"top_p={self.top_p}, spec_k={self.spec_k})")


GREEDY = SamplingSpec()

# Priority classes, highest first: the admission queue pops
# ``interactive`` ahead of ``batch``, and only ``batch`` residents are
# preemptible when the interactive TTFT SLO degrades.
PRIORITIES = ("interactive", "batch")


class QueueFullError(RuntimeError):
    """Admission queue at capacity: the front-end returns 429 with
    ``Retry-After: retry_after`` (seconds).  Deliberately NOT a
    ValueError — a full queue is backpressure, not a client error."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = int(retry_after)


class RequestCancelled(RuntimeError):
    """Terminal status ``cancelled``: the client went away (or an
    in-process caller cancelled the group).  The engine evicts the
    request's slots at the next step boundary; the front-end maps
    this to 499 (client closed request — nobody is listening)."""


class DeadlineExceeded(RuntimeError):
    """Terminal status ``expired``: the request's deadline passed
    before it completed.  Delivered at a step boundary like a cancel
    (partial work is discarded, the slot frees); the front-end maps
    this to 504."""


class ShedError(RuntimeError):
    """Terminal status ``shed``: graceful overload — the request was
    refused or dropped WITHOUT being started (its class queue
    deadline passed before any engine attention, the server is
    draining, or a bounded front-end wait gave up on a wedged
    engine).  Maps to 503 with a structured machine-readable
    ``reason`` so clients and load balancers can tell shed classes
    apart."""

    def __init__(self, msg: str, reason: str = "overload",
                 retry_after: Optional[int] = None):
        super().__init__(msg)
        self.reason = str(reason)
        self.retry_after = retry_after


class PoisonedRequest(RuntimeError):
    """Terminal status ``poisoned``: the fault-containment layer
    isolated THIS request as the one whose computation keeps failing
    the shared decode step (quarantine bisection,
    engine._quarantine_step) and failed it alone — its co-tenants
    were requeued and resumed token-identically.  Maps to 500 with
    the machine-readable ``reason: poisoned_request`` so clients can
    tell "my request breaks the model" apart from "the server is
    broken" (which sheds 503 ``engine_down`` instead)."""

    reason = "poisoned_request"


def terminal_status(err: Optional[BaseException]) -> str:
    """Map a terminal error to the request's lifecycle status name
    (the ``status`` field on RequestGroup, span names, counters)."""
    if err is None:
        return "complete"
    if isinstance(err, ShedError):
        return "shed"
    if isinstance(err, DeadlineExceeded):
        return "expired"
    if isinstance(err, RequestCancelled):
        return "cancelled"
    if isinstance(err, PoisonedRequest):
        return "poisoned"
    return "failed"


class SchedulerPolicy:
    """Continuous-batching knobs (docs/SERVING.md).

    ``n_slots``: decode-slot pool size — the physical batch width of
    every decode step and the KV memory bound (n_slots x one full
    per-request cache).  ``queue_depth``: max ROWS waiting for a slot
    before the front-end sheds load.  ``prefill_chunk``: default
    prompt-chunk length for interleaved prefill (None = whole prompt
    in one piece; per-request ``prefill_chunk`` overrides).
    ``idle_prefill_burst``: prefill chunks per tick while NO decode is
    running (when decodes are active, exactly one chunk per step
    boundary).  ``decode_window``: max decode steps fused into one
    device dispatch when no admission could happen sooner anyway
    (engine._pick_window drops to single steps whenever a queued
    request or a possible eos eviction is in play, and never fuses
    past the earliest budget eviction — the window saves dispatch
    overhead, never scheduling granularity).  ``retry_after_s``: the
    Retry-After hint on 429s.
    """

    def __init__(self, *, n_slots: int = 8, queue_depth: int = 64,
                 prefill_chunk: Optional[int] = None,
                 idle_prefill_burst: int = 4, decode_window: int = 8,
                 retry_after_s: int = 1,
                 default_priority: str = "interactive",
                 batch_queue_depth: Optional[int] = None,
                 queue_deadline_s: Optional[float] = None,
                 batch_queue_deadline_s: Optional[float] = None,
                 slo_ttft_s: Optional[float] = None,
                 kv_paged: bool = False, kv_page_tokens: int = 64,
                 kv_pages: Optional[int] = None,
                 kv_lazy: bool = False,
                 spec_k_cap: int = 4):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        if queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1; got {queue_depth}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1; got {prefill_chunk}")
        if decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1; got {decode_window}")
        if default_priority not in PRIORITIES:
            raise ValueError(
                f"default_priority must be one of {PRIORITIES}; "
                f"got {default_priority!r}")
        if batch_queue_depth is not None and batch_queue_depth < 1:
            raise ValueError(f"batch_queue_depth must be >= 1; got "
                             f"{batch_queue_depth}")
        for name, v in (("queue_deadline_s", queue_deadline_s),
                        ("batch_queue_deadline_s",
                         batch_queue_deadline_s),
                        ("slo_ttft_s", slo_ttft_s)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0; got {v}")
        self.n_slots = int(n_slots)
        self.queue_depth = int(queue_depth)
        self.prefill_chunk = prefill_chunk
        self.idle_prefill_burst = max(1, int(idle_prefill_burst))
        self.decode_window = int(decode_window)
        self.retry_after_s = int(retry_after_s)
        # Lifecycle knobs: the default priority class for requests
        # that don't declare one; per-class queue depth (batch
        # defaults to the interactive depth) and queue DEADLINES (a
        # queued request with zero engine attention past its class
        # deadline is shed with 503 instead of rotting); and the
        # interactive-TTFT SLO target arming batch preemption
        # (engine._maybe_preempt — None disables preemption).
        self.default_priority = default_priority
        self.batch_queue_depth = int(batch_queue_depth) \
            if batch_queue_depth is not None else self.queue_depth
        self.queue_deadline_s = queue_deadline_s
        self.batch_queue_deadline_s = batch_queue_deadline_s
        self.slo_ttft_s = slo_ttft_s
        # Paged-KV knobs (serving/paged.py): ``kv_paged`` swaps the
        # fixed-lane slot cache for the block-table page pool;
        # ``kv_page_tokens`` is the page size in positions;
        # ``kv_pages`` the pool size in pages (None = the fixed-lane
        # footprint, n_slots x ceil(max_position / page_tokens) — the
        # equal-memory default the bench A/Bs against).
        # ``spec_k_cap`` bounds the pool's speculative draft width —
        # a spec-capable pool's verify chunks write cap+1 wide for
        # EVERY resident, so paged admission reserves that slack per
        # slot (the server passes its --spec-k here).
        if kv_page_tokens < 8:
            raise ValueError(
                f"kv_page_tokens must be >= 8; got {kv_page_tokens}")
        if kv_pages is not None and kv_pages < 1:
            raise ValueError(f"kv_pages must be >= 1; got {kv_pages}")
        if spec_k_cap < 1:
            raise ValueError(
                f"spec_k_cap must be >= 1; got {spec_k_cap}")
        # ``kv_lazy`` (the --kv-lazy knob): LAZY page reservation —
        # admission reserves prompt + one dispatch span instead of
        # the full budget, tables grow at step boundaries, and pool
        # exhaustion preempts the resident with the most remaining
        # budget (token-identical resume; serving/paged.py
        # "RESERVATION DISCIPLINE").
        if kv_lazy and not kv_paged:
            raise ValueError(
                "kv_lazy requires kv_paged (lazy growth is a page-"
                "reservation policy; fixed lanes have no pages)")
        self.kv_paged = bool(kv_paged)
        self.kv_page_tokens = int(kv_page_tokens)
        self.kv_pages = int(kv_pages) if kv_pages is not None else None
        self.kv_lazy = bool(kv_lazy)
        self.spec_k_cap = int(spec_k_cap)

    def class_queue_depth(self, priority: str) -> int:
        return self.batch_queue_depth if priority == "batch" \
            else self.queue_depth

    def class_queue_deadline(self, priority: str) -> Optional[float]:
        return self.batch_queue_deadline_s if priority == "batch" \
            else self.queue_deadline_s

    def prefill_budget(self, decodes_active: bool,
                       free_slots: int = 1) -> int:
        """Prefill chunks allowed at this step boundary.  While
        decodes run, at least one chunk per boundary (interleaved
        prefill must make progress) and up to one per FREE slot — an
        empty slot burns a full-width decode step on garbage every
        boundary it stays empty, which costs more than the prefill
        chunks that would fill it.  Idle batch: burst."""
        if not decodes_active:
            return max(self.idle_prefill_burst, free_slots)
        return max(1, free_slots)

    @staticmethod
    def pow2_pieces(n: int) -> List[int]:
        """Split ``n`` prefill tokens into DESCENDING power-of-two
        pieces (binary decomposition: 39 -> [32, 4, 2, 1]).  Used for
        preemption-resume re-prefill, whose total length varies with
        the (data-dependent) preemption point: naive one-piece
        prefill would compile a fresh program per preempted request
        forever, where pow2 pieces bound the shape set to
        ~log2(max_position) programs that go warm after the first few
        preemptions — the zero-steady-state-recompile contract held
        on the resume path (pinned in tests/test_lifecycle.py).
        Chunked prefill is position-keyed cache extension, so the
        split changes compile keys, never tokens."""
        pieces: List[int] = []
        if n <= 0:
            return pieces
        b = 1 << (n.bit_length() - 1)
        while n:
            if n >= b:
                pieces.append(b)
                n -= b
            b >>= 1
        return pieces

    def chunk_plan(self, p_len: int, req_chunk: Optional[int]
                   ) -> List[int]:
        """Split a ``p_len`` prompt into per-boundary prefill pieces.
        Chunking is position-keyed cache mechanics (models/generate
        ``_prefill``): it changes scheduling and memory, never logits.
        """
        chunk = req_chunk if req_chunk is not None else self.prefill_chunk
        if chunk is None or chunk >= p_len:
            return [p_len]
        n_full, rem = divmod(p_len, chunk)
        return [chunk] * n_full + ([rem] if rem else [])


class Stream:
    """One prompt ROW moving through the engine: queued -> prefilling
    (chunk by chunk) -> resident in a decode slot -> done."""

    __slots__ = ("group", "row", "toks", "new", "eos_id", "sampling",
                 "base_key", "pieces", "filled", "cache", "logits",
                 "out", "slot", "pf_done", "t_prefill_start",
                 "t_admit", "t_done", "d_cache", "spec_rounds",
                 "spec_drafted", "spec_accepted", "sid", "events",
                 "pf_toks", "resume", "kv_shared", "kv_epoch",
                 "last_slot", "preempts", "resumes", "blocked_t",
                 "evicted_for")

    def __init__(self, group: "RequestGroup", row: int,
                 toks: np.ndarray, new: int, eos_id: Optional[int],
                 pieces: List[int],
                 sampling: Optional[SamplingSpec] = None):
        self.group = group
        self.row = row
        self.toks = toks          # [1, p_len] int32
        # What prefill actually consumes: the prompt, or — after a
        # preemption — prompt ++ committed-tokens[:-1] (prepare_resume
        # below).  ``toks`` stays the prompt: results and prefix-cache
        # keys never see resume state.
        self.pf_toks = toks
        self.resume = False       # re-prefilling after a preemption
        self.new = new
        self.eos_id = eos_id
        self.sampling = sampling or GREEDY
        # fold_in(PRNGKey(seed), row) — materialized lazily (engine
        # _admit) so greedy streams never touch the PRNG at all
        self.base_key = None
        self.pieces = pieces      # remaining prefill piece lengths
        self.filled = 0           # prompt tokens already prefilled
        self.cache = None         # partial B=1 cache during prefill
        self.d_cache = None       # draft-model cache (spec streams)
        self.logits = None        # last-position logits once filled
        self.out: List[int] = []  # committed new tokens
        self.slot: Optional[int] = None
        self.pf_done = False      # prompt fully consumed (may still
        #                           be queued, waiting for a slot)
        self.t_prefill_start: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_done: Optional[float] = None
        # Telemetry: trace-track id (engine assigns one per stream at
        # submit) and, when the request asked for a ``timings`` block,
        # the (name, t0, t1, args) phase tuples the response renders.
        self.sid: Optional[int] = None
        self.events: Optional[List[tuple]] = None
        # Speculative accounting (rounds consumed before the stream
        # finished; drafted/accepted feed the acceptance-rate
        # histogram at completion).
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        # Paged-KV: PINNED shared prefix page ids this stream will
        # map at admission (server prefix hits set it via
        # engine.submit).  The engine owns the pins from submit on —
        # insert transfers them into the slot's table, every
        # pre-admission terminal path unpins them
        # (engine._release_stream_kv).
        self.kv_shared: Optional[tuple] = None
        # Pool epoch the shared pins were taken under (paged prefix
        # hits; engine._validate_shared_epoch drops pins from a pool
        # generation that crash recovery has since rebuilt).
        self.kv_epoch: Optional[int] = None
        # Debuggability (serving/debug.py): the last slot this stream
        # occupied (``slot`` clears at eviction; the access log and
        # the history record want the id after the fact), preempt/
        # resume counts (a resumed request must be distinguishable
        # from a straight-through one in the log), and the moment the
        # stream became BLOCKED at the admission gate — on a slot, or
        # (paged) on free pages (None when not blocked; the wait span
        # in its causal timeline, closed with what unblocked it).
        self.last_slot: Optional[int] = None
        self.preempts = 0
        self.resumes = 0
        self.blocked_t: Optional[float] = None
        # Lazy-KV livelock guard (engine._ensure_lazy_growth): the
        # stream this one was exhaustion-evicted FOR.  While set, the
        # admission gate skips this stream — the freed pages must
        # reach the growth-blocked beneficiary before its own evictee
        # can take them back — and the engine clears it the moment a
        # growth pass completes (or the beneficiary goes terminal).
        self.evicted_for: Optional["Stream"] = None

    @property
    def p_len(self) -> int:
        return self.toks.shape[1]

    # ptpu: lockfree[single owner: a preempted stream is operated on by exactly one thread, ownership moves through locked queues]
    def prepare_resume(self, pieces: List[int]) -> None:
        """Reset this PREEMPTED stream for re-prefill + re-admission
        with its generated-so-far prefix, so no token is resampled.

        The cache is rebuilt by prefilling ``prompt ++ out[:-1]`` (the
        chunked-prefill exactness contract: prefill of the true
        committed prefix equals having decoded it incrementally, per
        model — the draft cache included for speculative streams);
        re-admission then feeds ``out[-1]`` at its original position
        with ``next_index == len(out)``, so token ``len(out)`` is
        drawn with exactly the position key the uninterrupted run
        would have used.  Token-identical resumption is what makes
        preemption safe under the RNG determinism contract (pinned in
        tests/test_lifecycle.py across plain/sampled/spec)."""
        assert self.out, "preempted stream with no committed tokens"
        self.resume = True
        if len(self.out) > 1:
            self.pf_toks = np.concatenate(
                [self.toks,
                 np.asarray([self.out[:-1]], np.int32)], axis=1)
        else:
            self.pf_toks = self.toks
        self.pieces = pieces
        self.filled = 0
        self.pf_done = False
        self.cache = None
        self.d_cache = None
        self.logits = None
        self.slot = None

    def done(self) -> bool:
        if len(self.out) >= self.new:
            return True
        return self.eos_id is not None and bool(self.out) \
            and self.out[-1] == self.eos_id

    def result_row(self) -> np.ndarray:
        """prompt ++ new tokens, eos-padded to the requested budget —
        exactly solo ``generate``'s eos-freeze semantics (finished rows
        keep emitting eos), so engine responses are comparable
        token-for-token with solo ones."""
        toks = list(self.out)
        if len(toks) < self.new:
            toks += [self.eos_id] * (self.new - len(toks))
        return np.concatenate(
            [self.toks[0], np.asarray(toks, np.int32)])


class RequestGroup:
    """One /generate request: B streams plus completion/timing state."""

    def __init__(self, rows: np.ndarray, new: int,
                 eos_id: Optional[int], pieces_per_row: List[int],
                 sampling: Optional[SamplingSpec] = None, *,
                 priority: str = "interactive"):
        # Request ID — the correlation key across the response header,
        # access log, trace spans, and the request-history record.
        # Set by engine.submit (inbound X-Request-Id, or generated)
        # so every group has one however it was constructed.
        self.rid: Optional[str] = None
        self.rows = rows
        self.new = new
        self.sampling = sampling or GREEDY
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}; "
                             f"got {priority!r}")
        self.priority = priority
        # Absolute perf_counter deadline (None = immortal), armed by
        # engine.submit RELATIVE to t_submit (there is deliberately
        # no constructor path: every deadline shares that one
        # convention).  Checked at step boundaries by the engine
        # sweep and by the front-end wait loop — never inside a
        # compiled step program.
        self.deadline: Optional[float] = None
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        # Lifecycle: a cancel/deadline/shed request lands here from
        # ANY thread (request_cancel); the engine delivers it — evict
        # slots, drop queue entries, fail the group — at its next
        # step boundary.  ``status`` is the terminal state name.
        self.cancel_error: Optional[BaseException] = None
        self.status = "active"
        # Called (with the stream) on the engine thread the moment a
        # stream's prompt is fully prefilled, before slot admission —
        # the prefix cache's store-back hook (server._store_stream_
        # prefix), so sessions grow warm without a solo detour.
        self.on_prefilled = None
        # Prefix-cache hit provenance (server prefix hits): a small
        # dict — cached token count, shared-page count — carried into
        # the request-history record so a hit's cheap TTFT is
        # attributable after the fact.
        self.prefix_info: Optional[Dict] = None
        self.results: List[Optional[np.ndarray]] = [None] * rows.shape[0]
        self._pending = rows.shape[0]
        # record_timings: the request asked for a per-phase ``timings``
        # block — streams collect their span tuples (Stream.events) as
        # the engine emits them, so the response can render the same
        # lifecycle /trace records without scanning the shared ring.
        self.record_timings = False
        self.t_submit = time.perf_counter()
        self.t_first_prefill: Optional[float] = None
        self.t_first_admit: Optional[float] = None
        self.t_last_admit: Optional[float] = None
        self.t_done: Optional[float] = None
        self.streams = [
            Stream(self, i, rows[i:i + 1], new, eos_id,
                   list(pieces_per_row), self.sampling)
            for i in range(rows.shape[0])]

    def complete_row(self, stream: Stream) -> None:
        self.results[stream.row] = stream.result_row()
        self._pending -= 1
        if self._pending == 0:
            self.t_done = time.perf_counter()
            self.status = "complete"
            self.event.set()

    def fail(self, err: BaseException) -> None:
        if not self.event.is_set():
            self.error = err
            self.t_done = time.perf_counter()
            self.status = terminal_status(err)
            self.event.set()

    def request_cancel(self, err: BaseException) -> None:
        """Ask for this group's eviction at the next step boundary
        (idempotent; the first reason wins).  Safe from any thread —
        a single reference store the engine thread reads.  Callers
        outside the engine go through :meth:`DecodeEngine.cancel`,
        which also arms the sweep's fast-path flag — a bare
        request_cancel is only guaranteed delivery when something
        else (a deadline, a queue deadline) keeps the sweep on."""
        if self.cancel_error is None and not self.event.is_set():
            # ptpu: lockfree[single reference store read by the engine sweep; first-wins race is acceptable by contract]
            self.cancel_error = err

    def status_phase(self) -> str:
        """Where this request is in its lifecycle right now — for
        error messages and the cancelled/expired span args."""
        if self.t_first_admit is not None:
            return "decoding"
        if self.t_first_prefill is not None:
            return "prefilling"
        return "queued"

    def result(self) -> np.ndarray:
        return np.stack(self.results, axis=0)

    def breakdown(self):
        """(queue_s, prefill_s, decode_s) wall-clock phase split."""
        t0 = self.t_submit
        tp = self.t_first_prefill if self.t_first_prefill is not None \
            else (self.t_done or t0)
        ta = self.t_last_admit if self.t_last_admit is not None \
            else (self.t_done or tp)
        td = self.t_done if self.t_done is not None else ta
        return max(0.0, tp - t0), max(0.0, ta - tp), max(0.0, td - ta)


class AdmissionQueue:
    """Bounded PER-CLASS FIFO of streams awaiting prefill + a slot.

    ``submit`` is atomic per request (all B streams or none) so a
    multi-row request can never deadlock half-admitted against the
    depth bound, and lands in its group's PRIORITY class queue with
    that class's own depth bound.  ``head``/``pop_head`` are
    class-aware — ``interactive`` drains before ``batch`` (the
    "defer" half of preempt-or-defer; within one class, FIFO).
    ``requeue_front`` puts a PREEMPTED stream back at the head of its
    class, bypassing the depth bound (it was already admitted once —
    requeueing must never shed it).
    """

    def __init__(self, policy: SchedulerPolicy):
        self.policy = policy
        self._q: Dict[str, "deque[Stream]"] = {
            p: deque() for p in PRIORITIES}
        self._lock = threading.Lock()
        self.rejected = 0

    def __len__(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._q.values())

    def class_len(self, priority: str) -> int:
        with self._lock:
            return len(self._q[priority])

    def submit(self, group: RequestGroup) -> None:
        n = len(group.streams)
        cls = group.priority
        depth = self.policy.class_queue_depth(cls)
        if n > depth:
            # Usage error, not backpressure: a request wider than its
            # whole class queue can never be admitted even when idle,
            # so a retryable 429 would have a well-behaved client
            # retry forever.  ValueError maps to 400 at the HTTP
            # layer.
            raise ValueError(
                f"request has {n} rows but the {cls} admission queue "
                f"holds {depth}; raise --queue-depth or split the "
                f"batch")
        with self._lock:
            if len(self._q[cls]) + n > depth:
                self.rejected += 1
                raise QueueFullError(
                    f"{cls} admission queue full ({len(self._q[cls])}"
                    f"/{depth} rows waiting); retry after "
                    f"{self.policy.retry_after_s}s",
                    retry_after=self.policy.retry_after_s)
            self._q[cls].extend(group.streams)

    def head(self) -> Optional[Stream]:
        with self._lock:
            for p in PRIORITIES:
                if self._q[p]:
                    return self._q[p][0]
            return None

    def pop_head(self) -> Optional[Stream]:
        with self._lock:
            for p in PRIORITIES:
                if self._q[p]:
                    return self._q[p].popleft()
            return None

    def pop_stream(self, stream: Stream) -> bool:
        """Remove EXACTLY ``stream`` (admission pops the stream it
        prefilled, not "whatever is head now").  With one FIFO the
        two were interchangeable; with class-aware popping, an
        interactive submit landing between the engine's ``head()``
        and its pop would CHANGE the head — popping blind would drop
        the newcomer on the floor and leave the admitted stream
        queued for a second, state-corrupting admission."""
        with self._lock:
            q = self._q[stream.group.priority]
            if q and q[0] is stream:
                q.popleft()
                return True
            try:
                q.remove(stream)
                return True
            except ValueError:
                return False

    def requeue_front(self, stream: Stream) -> None:
        with self._lock:
            self._q[stream.group.priority].appendleft(stream)

    def requeue_back(self, stream: Stream) -> None:
        """Requeue an EXHAUSTION-evicted stream at the BACK of its
        class (bypassing the depth bound, like requeue_front — it
        was already admitted once, requeueing must never shed it).
        Back, not front: the eviction freed pages for someone else
        — everyone already waiting in the class, the growth-blocked
        beneficiary included, goes first (the structural half of the
        lazy-KV livelock guard; ``Stream.evicted_for`` is the
        cross-class half)."""
        with self._lock:
            self._q[stream.group.priority].append(stream)

    def snapshot(self) -> List[Stream]:
        """Every queued stream, pop order — the lifecycle sweep's
        read-only view (cancel/deadline/shed checks)."""
        with self._lock:
            return [s for p in PRIORITIES for s in self._q[p]]

    def drop_group(self, group: RequestGroup) -> None:
        """Remove a failed group's still-queued streams."""
        with self._lock:
            q = self._q[group.priority]
            self._q[group.priority] = deque(
                s for s in q if s.group is not group)
