"""Scheduler policy for the continuous-batching engine.

The engine (engine.py) decouples the LOGICAL workload (a stream of
requests with arbitrary prompt lengths and token budgets) from the
PHYSICAL batch (a fixed pool of decode slots): requests wait in a
bounded admission queue, are prefilled chunk-by-chunk between decode
steps, and enter a slot at a decode-step boundary.  This module owns
the passive pieces of that design:

- :class:`RequestGroup` / :class:`Stream` — one /generate request and
  its per-row decode streams (a B-row request is B independent
  streams: decode rows never interact, so rows of one request need not
  occupy adjacent slots or even be resident together).
- :class:`SamplingSpec` — the per-request (seed, temperature, top_k,
  top_p) every stream carries into its slot; temperature 0 is greedy,
  and sampled streams draw under the position-keyed RNG contract
  (models/generate), so tokens never depend on the schedule.
- :class:`AdmissionQueue` — the bounded FIFO between the HTTP
  front-end and the engine.  Submission is all-or-nothing per request;
  a full queue raises :class:`QueueFullError`, which the front-end
  maps to 429 + Retry-After (explicit backpressure instead of an
  unbounded thread pile-up).
- :class:`SchedulerPolicy` — the knobs: slot count, queue depth, the
  default prefill chunk, and how much prefill work may run per decode
  boundary (1 chunk while decodes are active — prefill must never
  starve the running batch — bursting only when the batch is idle).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np


class SamplingSpec:
    """Per-request sampling parameters carried by every engine stream.

    ``temperature == 0`` is greedy (the default — top_k/top_p are
    inert then, matching solo ``generate``); ``top_k=0`` / ``top_p=0``
    encode "disabled" so the whole spec vmaps into the slot step
    program as plain numbers.  ``seed`` anchors the position-keyed
    RNG contract (models/generate.sample_stream_keys): row ``r``'s
    i-th generated token is drawn with
    ``fold_in(fold_in(PRNGKey(seed), r), i)`` — a function of (seed,
    row, token index) only, never of slot id, engine step count, or
    co-tenancy — which is what makes engine output independent of the
    admission schedule.

    ``spec_k > 0`` marks the request SPECULATIVE: its slots draft
    ``spec_k`` tokens per round from the engine's draft model and
    commit a variable accepted prefix (budget accounting stays in
    COMMITTED tokens — a stream is done when ``len(out)`` reaches its
    budget, however many rounds that took).  Speculative randomness
    is position-keyed too (per-(token index, lane) keys, see
    models/generate._spec_verify_row), so co-tenancy never changes a
    speculative response either.
    """

    __slots__ = ("seed", "temperature", "top_k", "top_p", "spec_k")

    def __init__(self, seed: int = 0, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 spec_k: int = 0):
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k) if top_k else 0
        self.top_p = float(top_p) if top_p else 0.0
        self.spec_k = int(spec_k) if spec_k else 0

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0

    @property
    def speculative(self) -> bool:
        return self.spec_k > 0

    def __repr__(self) -> str:  # debuggability in engine dumps
        return (f"SamplingSpec(seed={self.seed}, "
                f"temperature={self.temperature}, top_k={self.top_k}, "
                f"top_p={self.top_p}, spec_k={self.spec_k})")


GREEDY = SamplingSpec()


class QueueFullError(RuntimeError):
    """Admission queue at capacity: the front-end returns 429 with
    ``Retry-After: retry_after`` (seconds).  Deliberately NOT a
    ValueError — a full queue is backpressure, not a client error."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = int(retry_after)


class SchedulerPolicy:
    """Continuous-batching knobs (docs/SERVING.md).

    ``n_slots``: decode-slot pool size — the physical batch width of
    every decode step and the KV memory bound (n_slots x one full
    per-request cache).  ``queue_depth``: max ROWS waiting for a slot
    before the front-end sheds load.  ``prefill_chunk``: default
    prompt-chunk length for interleaved prefill (None = whole prompt
    in one piece; per-request ``prefill_chunk`` overrides).
    ``idle_prefill_burst``: prefill chunks per tick while NO decode is
    running (when decodes are active, exactly one chunk per step
    boundary).  ``decode_window``: max decode steps fused into one
    device dispatch when no admission could happen sooner anyway
    (engine._pick_window drops to single steps whenever a queued
    request or a possible eos eviction is in play, and never fuses
    past the earliest budget eviction — the window saves dispatch
    overhead, never scheduling granularity).  ``retry_after_s``: the
    Retry-After hint on 429s.
    """

    def __init__(self, *, n_slots: int = 8, queue_depth: int = 64,
                 prefill_chunk: Optional[int] = None,
                 idle_prefill_burst: int = 4, decode_window: int = 8,
                 retry_after_s: int = 1):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1; got {n_slots}")
        if queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1; got {queue_depth}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1; got {prefill_chunk}")
        if decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1; got {decode_window}")
        self.n_slots = int(n_slots)
        self.queue_depth = int(queue_depth)
        self.prefill_chunk = prefill_chunk
        self.idle_prefill_burst = max(1, int(idle_prefill_burst))
        self.decode_window = int(decode_window)
        self.retry_after_s = int(retry_after_s)

    def prefill_budget(self, decodes_active: bool,
                       free_slots: int = 1) -> int:
        """Prefill chunks allowed at this step boundary.  While
        decodes run, at least one chunk per boundary (interleaved
        prefill must make progress) and up to one per FREE slot — an
        empty slot burns a full-width decode step on garbage every
        boundary it stays empty, which costs more than the prefill
        chunks that would fill it.  Idle batch: burst."""
        if not decodes_active:
            return max(self.idle_prefill_burst, free_slots)
        return max(1, free_slots)

    def chunk_plan(self, p_len: int, req_chunk: Optional[int]
                   ) -> List[int]:
        """Split a ``p_len`` prompt into per-boundary prefill pieces.
        Chunking is position-keyed cache mechanics (models/generate
        ``_prefill``): it changes scheduling and memory, never logits.
        """
        chunk = req_chunk if req_chunk is not None else self.prefill_chunk
        if chunk is None or chunk >= p_len:
            return [p_len]
        n_full, rem = divmod(p_len, chunk)
        return [chunk] * n_full + ([rem] if rem else [])


class Stream:
    """One prompt ROW moving through the engine: queued -> prefilling
    (chunk by chunk) -> resident in a decode slot -> done."""

    __slots__ = ("group", "row", "toks", "new", "eos_id", "sampling",
                 "base_key", "pieces", "filled", "cache", "logits",
                 "out", "slot", "pf_done", "t_prefill_start",
                 "t_admit", "t_done", "d_cache", "spec_rounds",
                 "spec_drafted", "spec_accepted", "sid", "events")

    def __init__(self, group: "RequestGroup", row: int,
                 toks: np.ndarray, new: int, eos_id: Optional[int],
                 pieces: List[int],
                 sampling: Optional[SamplingSpec] = None):
        self.group = group
        self.row = row
        self.toks = toks          # [1, p_len] int32
        self.new = new
        self.eos_id = eos_id
        self.sampling = sampling or GREEDY
        # fold_in(PRNGKey(seed), row) — materialized lazily (engine
        # _admit) so greedy streams never touch the PRNG at all
        self.base_key = None
        self.pieces = pieces      # remaining prefill piece lengths
        self.filled = 0           # prompt tokens already prefilled
        self.cache = None         # partial B=1 cache during prefill
        self.d_cache = None       # draft-model cache (spec streams)
        self.logits = None        # last-position logits once filled
        self.out: List[int] = []  # committed new tokens
        self.slot: Optional[int] = None
        self.pf_done = False      # prompt fully consumed (may still
        #                           be queued, waiting for a slot)
        self.t_prefill_start: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_done: Optional[float] = None
        # Telemetry: trace-track id (engine assigns one per stream at
        # submit) and, when the request asked for a ``timings`` block,
        # the (name, t0, t1, args) phase tuples the response renders.
        self.sid: Optional[int] = None
        self.events: Optional[List[tuple]] = None
        # Speculative accounting (rounds consumed before the stream
        # finished; drafted/accepted feed the acceptance-rate
        # histogram at completion).
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0

    @property
    def p_len(self) -> int:
        return self.toks.shape[1]

    def done(self) -> bool:
        if len(self.out) >= self.new:
            return True
        return self.eos_id is not None and bool(self.out) \
            and self.out[-1] == self.eos_id

    def result_row(self) -> np.ndarray:
        """prompt ++ new tokens, eos-padded to the requested budget —
        exactly solo ``generate``'s eos-freeze semantics (finished rows
        keep emitting eos), so engine responses are comparable
        token-for-token with solo ones."""
        toks = list(self.out)
        if len(toks) < self.new:
            toks += [self.eos_id] * (self.new - len(toks))
        return np.concatenate(
            [self.toks[0], np.asarray(toks, np.int32)])


class RequestGroup:
    """One /generate request: B streams plus completion/timing state."""

    def __init__(self, rows: np.ndarray, new: int,
                 eos_id: Optional[int], pieces_per_row: List[int],
                 sampling: Optional[SamplingSpec] = None):
        self.rows = rows
        self.new = new
        self.sampling = sampling or GREEDY
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        # Called (with the stream) on the engine thread the moment a
        # stream's prompt is fully prefilled, before slot admission —
        # the prefix cache's store-back hook (server._store_stream_
        # prefix), so sessions grow warm without a solo detour.
        self.on_prefilled = None
        self.results: List[Optional[np.ndarray]] = [None] * rows.shape[0]
        self._pending = rows.shape[0]
        # record_timings: the request asked for a per-phase ``timings``
        # block — streams collect their span tuples (Stream.events) as
        # the engine emits them, so the response can render the same
        # lifecycle /trace records without scanning the shared ring.
        self.record_timings = False
        self.t_submit = time.perf_counter()
        self.t_first_prefill: Optional[float] = None
        self.t_first_admit: Optional[float] = None
        self.t_last_admit: Optional[float] = None
        self.t_done: Optional[float] = None
        self.streams = [
            Stream(self, i, rows[i:i + 1], new, eos_id,
                   list(pieces_per_row), self.sampling)
            for i in range(rows.shape[0])]

    def complete_row(self, stream: Stream) -> None:
        self.results[stream.row] = stream.result_row()
        self._pending -= 1
        if self._pending == 0:
            self.t_done = time.perf_counter()
            self.event.set()

    def fail(self, err: BaseException) -> None:
        if not self.event.is_set():
            self.error = err
            self.t_done = time.perf_counter()
            self.event.set()

    def result(self) -> np.ndarray:
        return np.stack(self.results, axis=0)

    def breakdown(self):
        """(queue_s, prefill_s, decode_s) wall-clock phase split."""
        t0 = self.t_submit
        tp = self.t_first_prefill if self.t_first_prefill is not None \
            else (self.t_done or t0)
        ta = self.t_last_admit if self.t_last_admit is not None \
            else (self.t_done or tp)
        td = self.t_done if self.t_done is not None else ta
        return max(0.0, tp - t0), max(0.0, ta - tp), max(0.0, td - ta)


class AdmissionQueue:
    """Bounded FIFO of streams awaiting prefill + a slot.

    ``submit`` is atomic per request (all B streams or none) so a
    multi-row request can never deadlock half-admitted against the
    depth bound.  The engine pops from the head only (FIFO — no
    reordering policy yet; the policy hook is SchedulerPolicy).
    """

    def __init__(self, policy: SchedulerPolicy):
        self.policy = policy
        self._q: "deque[Stream]" = deque()
        self._lock = threading.Lock()
        self.rejected = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def submit(self, group: RequestGroup) -> None:
        n = len(group.streams)
        if n > self.policy.queue_depth:
            # Usage error, not backpressure: a request wider than the
            # whole queue can never be admitted even when idle, so a
            # retryable 429 would have a well-behaved client retry
            # forever.  ValueError maps to 400 at the HTTP layer.
            raise ValueError(
                f"request has {n} rows but the admission queue holds "
                f"{self.policy.queue_depth}; raise --queue-depth or "
                f"split the batch")
        with self._lock:
            if len(self._q) + n > self.policy.queue_depth:
                self.rejected += 1
                raise QueueFullError(
                    f"admission queue full ({len(self._q)}/"
                    f"{self.policy.queue_depth} rows waiting); retry "
                    f"after {self.policy.retry_after_s}s",
                    retry_after=self.policy.retry_after_s)
            self._q.extend(group.streams)

    def head(self) -> Optional[Stream]:
        with self._lock:
            return self._q[0] if self._q else None

    def pop_head(self) -> Optional[Stream]:
        with self._lock:
            return self._q.popleft() if self._q else None

    def drop_group(self, group: RequestGroup) -> None:
        """Remove a failed group's still-queued streams."""
        with self._lock:
            self._q = deque(s for s in self._q if s.group is not group)
