"""Native model server: the zoo's decode stack behind HTTP.

The reference's serving story is `V1Service` — it schedules an opaque
user container and port-forwards to it (SURVEY.md §2.4); the model
server inside is the user's problem.  Here the framework owns the
decode loop, so it ships the server too: one process, stdlib HTTP
(same no-dependency stance as the control plane), jit-compiled
generate with a shape-bucketed compile cache.

Endpoints:

- ``GET  /healthz``  -> ``{"status": "ok", ...}`` (readiness; also the
  operator's gang-health convention)
- ``GET  /info``     -> model name, config summary, quantization flags
- ``GET  /metrics``  -> Prometheus text: counters, phase summaries,
  and the latency histograms (telemetry.py)
- ``GET  /trace``    -> Chrome trace-event JSON of the telemetry ring
  (request lifecycle spans + the engine step timeline) — load it in
  Perfetto or chrome://tracing
- ``POST /profile/start`` / ``POST /profile/stop`` -> guarded,
  single-flight ``jax.profiler`` trace into the server's
  ``profile_dir`` (400 when started without one)
- ``POST /prefill``  -> register a prompt (prefix) in the PREFIX
  CACHE: its KV prefill is stored on device (LRU, ``prefix_cache``
  entries) and later /generate requests whose prompt starts with it
  skip that prefill — the system-prompt serving win.  Hits extend and
  re-store, so growing sessions stay warm.  Exact by the
  prefill/continue split contract (models/generate.py).
- ``POST /generate`` -> ``{"prompt": [ids] | [[ids], ...],
  "max_new_tokens": N, "temperature": t, "top_k": k, "top_p": p,
  "eos_id": e, "num_beams": B, "speculative": bool, "spec_k": K,
  "seed": s, "prefill_chunk": C}`` -> tokens + timing (speculative
  needs a server-side draft model; greedy by default, and with
  temperature/top_k/top_p it runs rejection speculative sampling —
  exact target-distribution samples for any draft)

Shape discipline: each distinct (batch, prompt_len, max_new_tokens,
decode-mode) compiles once and is cached.  Prompts are NOT padded:
the zoo's decode path has no attention-mask input, so left-padding
would let real tokens attend to pad positions (silently wrong
output).  Clients with ragged traffic should bucket prompt lengths
themselves; rows in one request must share a length (the continuous-
batching engine mixes LENGTHS freely across requests — only rows
within one request body share a shape).

Concurrency — the CONTINUOUS-BATCHING engine (engine.py, default):
greedy AND sampled (non-beam, non-speculative) requests become
per-row decode streams over a fixed pool of decode slots; admission
happens at decode-step boundaries into slots freed by eos/budget
eviction, long prompts prefill in chunks interleaved between decode
steps, and the front-end sheds load with 429 + Retry-After once the
bounded admission queue fills.  Engine responses are exact vs solo
execution: greedy rows never interact (eos-frozen rows pad to
budget), and sampled rows draw through the POSITION-KEYED RNG
contract (models/generate.generate_positional — token i's key is
fold_in(fold_in(PRNGKey(seed), row), i), a function of the request
alone), so co-tenancy never changes a sampled response.
``batching="coalesce"`` selects the legacy whole-request coalescer
(legacy.py — the measured baseline; sampled requests decode solo
there), ``batching="off"`` serializes every request (the A/B floor).
SPECULATIVE decoder-only requests default to the engine too when the
server owns a draft model: spec slots draft/verify/commit a variable
accepted prefix per round under the same position-keyed RNG contract
(engine output == ``generate_speculative``'s seed mode), so a single
speculative client no longer holds the device lock for a whole
decode.  Beam requests always take the solo path (the per-beam cache
schedule would change their outputs if merged); requests that fall
back to solo are counted per kind in /info's routing report.
"""

from __future__ import annotations

import collections
import contextlib
import json
import select
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from ._lru import lru_get
from .debug import (RequestHistory, StallWatchdog, events_to_dicts,
                    new_request_id, sanitize_request_id)
from .engine import DecodeEngine
from .faults import FaultPlan, SocketReset
from .forensics import ForensicsCore, compute_ledger
from .legacy import RequestCoalescer
from .paged import WirePayloadError, pack_spilled, unpack_spilled
from .radix import RadixPrefixIndex
from .recovery import EngineSupervisor
from .scheduler import (DeadlineExceeded, PRIORITIES,
                        PoisonedRequest, QueueFullError,
                        RequestCancelled, SamplingSpec,
                        SchedulerPolicy, ShedError)
from .telemetry import (ProfileSession, Telemetry,
                        render_compile_cache, render_histogram)

BATCHING_MODES = ("continuous", "coalesce", "off")

# Disaggregated-serving roles (docs/SERVING.md "Disaggregated
# serving"): "both" = monolithic (the default, byte-for-byte today's
# behavior), "prefill" = prompt prefill + wire export only (rejects
# /generate), "decode" = full serving expected to ADMIT handed-off
# prefills over the wire-fetch lane.
ROLES = ("prefill", "decode", "both")


class _PagedPrefix:
    """Radix payload for a PAGE-BACKED prefix entry: the stored
    prompt's KV lives in the engine's page pool (one reference per
    page held by this entry — shared pages referenced, never copied),
    not in a private contiguous cache.  ``logits`` are the last-
    position prefill logits (what a full-length hit seeds decode
    with)."""

    __slots__ = ("pages", "n_tokens", "logits")

    def __init__(self, pages, n_tokens: int, logits):
        self.pages = tuple(int(p) for p in pages)
        self.n_tokens = int(n_tokens)
        self.logits = logits


class _SpilledPrefix:
    """Radix payload for a HOST-TIER prefix entry (the spill tier,
    ``--kv-host-spill-bytes``): the stored prompt's KV lives in host
    RAM — one np array per paged cache leaf, gathered by the
    sanctioned ``PagedSlotKVManager.spill_pages`` helper when page
    pressure evicted the entry from the device pool — instead of
    being dropped.  A hit re-materializes via ``device_put``
    (``manager.rematerialize``) and opportunistically PROMOTES back
    to device pages.  Host buffers reference no device state, so
    spilled entries SURVIVE a crash-recovery pool rebuild (the epoch
    contract extension, docs/DESIGN.md)."""

    __slots__ = ("leaves", "n_tokens", "logits", "nbytes")

    def __init__(self, leaves, n_tokens: int, logits):
        self.leaves = list(leaves)
        self.n_tokens = int(n_tokens)
        self.logits = logits            # host np copy
        self.nbytes = int(sum(a.nbytes for a in leaves
                              if a is not None)) \
            + (int(logits.nbytes) if hasattr(logits, "nbytes") else 0)


PrefixHit = collections.namedtuple(
    "PrefixHit", ["p_cached", "logits", "cache", "pins", "source"],
    defaults=("device",))
"""One prefix-cache lookup result: ``p_cached`` tokens of stored
prefill, the stored last-position ``logits``, a CONTIGUOUS ``cache``
holding them (materialized from pool pages in paged mode), and
``pins`` — still-pinned FULL-page ids the engine path maps read-only
into the admitted slot's table (empty for legacy entries).  The
caller owns the pins until ``engine.submit(shared_pages=pins)``
returns; every other outcome must unpin them.  ``source`` records
which tier served the hit (``"device"`` or ``"host"``) so responses
and history records can attribute the prefix's provenance."""


class PrefixFetchPolicy:
    """The wire-fetch cost curve: fetch a spilled prefix from a
    holder replica only when the expected wire cost beats the local
    re-prefill cost.  A spilled LOCAL hit lands at ~0.26x of a
    re-prefill miss (the PR 12 measurement — ``remat_ratio``); a WIRE
    hit pays that same re-materialization PLUS one round trip and the
    body transfer, so the curve is::

        rtt + nbytes / wire_bytes_per_s + remat_ratio * reprefill
            < reprefill,   where reprefill = n_tokens / prefill_tok_per_s

    plus two hard gates — a minimum match length (tiny prefixes
    re-prefill faster than any network hop) and a byte ceiling (one
    giant payload must not monopolize the fetch path).  Pure and
    deterministic, so the thresholds unit-test without a fleet.  The
    client evaluates it twice: once before dialing (``nbytes=0`` —
    only the token gate can veto yet) and again on the holder's
    Content-Length BEFORE reading the body, so a policy veto costs
    headers, never the transfer."""

    def __init__(self, *, min_tokens: int = 16,
                 max_bytes: int = 1 << 30,
                 wire_bytes_per_s: float = 1e9,
                 rtt_s: float = 2e-3,
                 prefill_tok_per_s: float = 4e3,
                 remat_ratio: float = 0.26):
        if min_tokens < 1:
            raise ValueError(
                f"min_tokens must be >= 1; got {min_tokens}")
        if max_bytes < 1:
            raise ValueError(
                f"max_bytes must be >= 1; got {max_bytes}")
        if wire_bytes_per_s <= 0 or prefill_tok_per_s <= 0:
            raise ValueError(
                "wire_bytes_per_s and prefill_tok_per_s must be > 0")
        if rtt_s < 0 or not 0.0 <= remat_ratio < 1.0:
            raise ValueError(
                "need rtt_s >= 0 and 0 <= remat_ratio < 1")
        self.min_tokens = int(min_tokens)
        self.max_bytes = int(max_bytes)
        self.wire_bytes_per_s = float(wire_bytes_per_s)
        self.rtt_s = float(rtt_s)
        self.prefill_tok_per_s = float(prefill_tok_per_s)
        self.remat_ratio = float(remat_ratio)

    def should_fetch(self, n_tokens: int, nbytes: int, *,
                     wire_bytes_per_s: Optional[float] = None,
                     rtt_s: Optional[float] = None
                     ) -> Tuple[bool, str]:
        """``(ok, reason)`` — ``reason`` is the typed veto (the
        ``prefix_fetch_failed_total{reason=}`` label) or ``"ok"``.

        ``wire_bytes_per_s``/``rtt_s`` override the constructed
        constants for ONE evaluation: the router measures each link
        from completed fetches and handoffs (EWMA) and ships the
        estimates inside the ``prefix_hint``, so the gate runs on
        observed link truth instead of the static defaults whenever
        a measurement exists (ROADMAP item 3's calibration half)."""
        if n_tokens < self.min_tokens:
            return False, "below_min_tokens"
        if nbytes > self.max_bytes:
            return False, "over_max_bytes"
        bw = self.wire_bytes_per_s if wire_bytes_per_s is None \
            or wire_bytes_per_s <= 0 else float(wire_bytes_per_s)
        rtt = self.rtt_s if rtt_s is None or rtt_s < 0 \
            else float(rtt_s)
        reprefill_s = n_tokens / self.prefill_tok_per_s
        wire_s = (rtt + nbytes / bw
                  + self.remat_ratio * reprefill_s)
        if wire_s >= reprefill_s:
            return False, "wire_slower"
        return True, "ok"

    def describe(self) -> Dict[str, Any]:
        return {"min_tokens": self.min_tokens,
                "max_bytes": self.max_bytes,
                "wire_bytes_per_s": self.wire_bytes_per_s,
                "rtt_s": self.rtt_s,
                "prefill_tok_per_s": self.prefill_tok_per_s,
                "remat_ratio": self.remat_ratio}


class PagePins(tuple):
    """Pinned page ids + the pool EPOCH they were pinned under
    (``PagedSlotKVManager.pin`` returns it).  Pins cross thread and
    lock scopes between the lookup and the engine's admission; a
    crash-recovery pool rebuild in between bumps the epoch, which is
    how every consumer (submit, admission, unpin) recognizes the ids
    as dead and drops them BY REFERENCE instead of corrupting the
    fresh refcount accounting."""

    epoch: Optional[int] = None

    def __new__(cls, ids, epoch):
        self = super().__new__(cls, ids)
        self.epoch = epoch
        return self


# The response ``timings`` block and the history record's timeline
# render through the SAME function (docs/DESIGN.md: one source, the
# two surfaces cannot disagree).
_span_dicts = events_to_dicts


# Structural no-drift contract (tests/test_fleet_observability.py):
# EVERY key of engine.stats() must render on the server's /metrics
# under ``ptpu_serving_<key>``, under a rename listed here, or carry
# an explicit exemption reason below — earlier PRs re-pinned this
# counter by counter; the structural walk means a NEW engine counter
# that skips the /metrics surface fails tier-1 instead of shipping
# dark.
ENGINE_STATS_METRIC_RENAMES = {
    "expired_total": "ptpu_serving_deadline_expired_total",
    # The breaker state string renders as the 0/1 open gauge.
    "breaker_state": "ptpu_serving_breaker_open",
    # The per-site dict IS the labeled counter family.
    "faults_injected": "ptpu_serving_faults_injected_total",
    # The acceptance-rate histogram's four stats keys all render
    # through ONE telemetry.render_histogram family.
    "spec_accept_buckets": "ptpu_serving_spec_accept_rate",
    "spec_accept_hist": "ptpu_serving_spec_accept_rate",
    "spec_accept_sum": "ptpu_serving_spec_accept_rate",
    "spec_accept_count": "ptpu_serving_spec_accept_rate",
    # Recompile-sentinel counters (telemetry.render_compile_cache).
    "compile_cache_misses": "ptpu_serving_compile_cache_misses_total",
    "compile_cache_hits": "ptpu_serving_compile_cache_hits_total",
    "compile_cache_evictions":
        "ptpu_serving_compile_cache_evictions_total",
}
ENGINE_STATS_METRIC_EXEMPT = {
    "faults_injected_total":
        "sum of the labeled ptpu_serving_faults_injected_total{site=}"
        " series a scrape can compute",
    "compile_cache_by_kind":
        "per-kind split lives in /info's routing report; the totals "
        "render via render_compile_cache",
    "mesh": "topology dict; renders as ptpu_serving_mesh_devices + "
            "per-axis ptpu_serving_mesh_axis_size{axis=}",
}


def _int_param(v):
    """int() that refuses booleans: int(True) == 1 would silently
    accept {"num_beams": true} / {"prefill_chunk": true}."""
    if isinstance(v, bool):
        raise ValueError("expected an integer, got a boolean")
    return int(v)


def _parse_prompt_rows(req, max_batch: int):
    """Shared /generate + /prefill prompt validation: returns the
    row-wrapped token lists (one shared length, ints-not-bools,
    batch-capped)."""
    if not isinstance(req, dict):
        raise ValueError("request body must be a JSON object")
    rows = req.get("prompt")
    if rows is None:
        raise ValueError("missing 'prompt'")
    if not isinstance(rows, list):
        raise ValueError("'prompt' must be a list of token ids "
                         "or a list of rows")
    if rows and not isinstance(rows[0], list):
        rows = [rows]
    if not rows or not rows[0]:
        raise ValueError("prompt must contain at least one token")
    if len(rows) > max_batch:
        raise ValueError(f"batch {len(rows)} exceeds max_batch "
                         f"{max_batch}")
    if len({len(r) for r in rows}) != 1:
        # No silent padding: the decode path has no attention
        # mask, so padded positions would be attended to.
        raise ValueError(
            "all prompt rows must share one length (the decode "
            "path has no pad mask; bucket lengths client-side)")
    if any(not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in r) for r in rows):
        # bool is an int subclass: [true, false] must not silently
        # decode as tokens [1, 0].
        raise ValueError("prompt rows must be integer token ids")
    return rows


class FairLock:
    """``threading.Lock`` with FIFO-ish handoff — a turnstile guards
    entry, so a releasing thread that immediately re-acquires (the
    continuous-batching engine's step loop does exactly this, every
    boundary) queues BEHIND threads already waiting instead of
    barging past them.

    CPython locks are not fair: release wakes one waiter, but the
    releasing thread can re-acquire before the waiter is scheduled.
    Handler threads doing device work — a wire-fetch admit
    (rematerialize + promote), a direct ``/prefill``, a solo request
    — sit behind an engine loop that holds/releases the device lock
    back-to-back while decodes run, and measured waits reach
    hundreds of milliseconds per acquisition (~30x the actual device
    work).  The turnstile bounds every waiter to roughly one
    in-flight hold: acquire the door, then the inner lock, release
    the door once inside — a barger must first pass the door the
    oldest waiter still holds."""

    def __init__(self):
        self._door = threading.Lock()
        self._inner = threading.Lock()
        self._waiting = 0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if not blocking:
            if not self._door.acquire(False):
                return False
            try:
                return self._inner.acquire(False)
            finally:
                self._door.release()
        # Advisory waiter count (GIL-coarse, no extra lock): the
        # engine's window-fuse decision polls it to drop to
        # single-step granularity while external device work waits.
        self._waiting += 1
        try:
            if timeout is None or timeout < 0:
                with self._door:
                    return self._inner.acquire()
            deadline = time.monotonic() + timeout
            if not self._door.acquire(True, timeout):
                return False
            try:
                rem = max(0.0, deadline - time.monotonic())
                return self._inner.acquire(True, rem)
            finally:
                self._door.release()
        finally:
            self._waiting -= 1

    def waiters(self) -> int:
        """Threads currently blocked in :meth:`acquire` — including
        the engine loop itself when it is between holds; callers
        polling this from OFF-thread contexts only ever see their
        own wait excluded."""
        return self._waiting

    def release(self) -> None:
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class ModelServer:
    """Wraps one model + params; owns the compile cache, the lock
    serializing device work, and the continuous-batching engine (see
    module docstring)."""

    def __init__(self, model, variables, *, model_name: str = "model",
                 max_batch: int = 8, batching: Optional[str] = None,
                 coalesce: Optional[bool] = None,
                 n_slots: int = 8, queue_depth: int = 64,
                 prefill_chunk: Optional[int] = None,
                 decode_window: int = 8,
                 kv_paged: bool = False,
                 kv_page_tokens: int = 64,
                 kv_pages: Optional[int] = None,
                 kv_lazy: bool = False,
                 kv_host_spill_bytes: int = 0,
                 prefix_fetch: bool = False,
                 prefix_fetch_policy: Optional[
                     "PrefixFetchPolicy"] = None,
                 prefix_fetch_timeout_s: float = 5.0,
                 role: str = "both",
                 default_priority: str = "interactive",
                 batch_queue_depth: Optional[int] = None,
                 queue_deadline_s: Optional[float] = None,
                 batch_queue_deadline_s: Optional[float] = None,
                 slo_ttft_s: Optional[float] = None,
                 request_timeout_s: Optional[float] = 600.0,
                 prefix_cache: int = 4,
                 draft_model=None, draft_variables=None,
                 spec_k: int = 4,
                 mesh=None,
                 trace_buffer: int = 4096,
                 profile_dir: Optional[str] = None,
                 profile_every: int = 0,
                 profile_steps: int = 8,
                 access_log: bool = False,
                 sanitize: bool = False,
                 sanitize_max_hold_s: Optional[float] = None,
                 sanitize_report: Optional[str] = None,
                 request_history: int = 256,
                 stall_timeout_s: Optional[float] = None,
                 stall_dir: str = ".",
                 stall_queue_factor: float = 4.0,
                 forensics: bool = True,
                 exemplar_k: int = 4,
                 forensics_dir: Optional[str] = None,
                 sentry_window: int = 64,
                 sentry_baseline_windows: int = 4,
                 fault_plan=None,
                 supervise: bool = True,
                 info: Optional[Dict[str, Any]] = None):
        self.model = model
        self.variables = variables
        # FAULT INJECTION (serving/faults.py), disarmed by default:
        # ``fault_plan`` (a FaultPlan, a plan dict, or a JSON path —
        # `ptpu serve --fault-plan f.json`) arms the deterministic
        # seeded chaos harness across the engine's step/admission
        # sites, the prefix store, and the HTTP handler.  Disarmed,
        # every probe site is one attribute check.
        self.faults = FaultPlan.load(fault_plan) \
            if fault_plan is not None else None
        # Telemetry core (telemetry.py): ONE ring + histogram set
        # shared with the engine, so request spans and engine step
        # records land in the same /trace timeline.  trace_buffer=0
        # disables span recording (the bench A/B's "telemetry off"
        # arm); the latency histograms stay live — they are the
        # /metrics surface.
        self.telemetry = Telemetry(
            buffer=trace_buffer,
            exemplar_k=(int(exemplar_k) if forensics else 0))
        # Recompile sentinel (analysis/recompile.py): ONE counter set
        # shared by the server's fused/split program LRU, the
        # engine's prefill programs, and the slot pool's step/insert
        # programs — /metrics' compile_cache_misses_total and /info's
        # compile_cache report both read it, and each miss drops a
        # compile_miss instant on the trace's engine track.
        from ..analysis.recompile import RecompileSentinel

        self.recompile = RecompileSentinel(telemetry=self.telemetry)
        # Lock-order sanitizer (analysis/locksan.py), opt-in via
        # ``sanitize`` (the `ptpu serve --sanitize` flag and the
        # engine/serving tests): wraps every serving lock in a
        # recording proxy that raises on lock-order inversion and
        # (when ``sanitize_max_hold_s`` is set) on device_lock holds
        # past the limit.  Off by default — the bench keeps it off
        # and documents why (benchmarks/bench_serving_load.py).
        self.sanitizer = None
        if sanitize:
            from ..analysis.locksan import LockSanitizer

            self.sanitizer = LockSanitizer(
                max_hold_s={"device_lock": sanitize_max_hold_s}
                if sanitize_max_hold_s is not None else None)
        # Machine-readable dump of the observed acquisition graph
        # (the same dict /info reports), written at close() — the
        # offline half of the static ⊆ runtime lock-graph
        # cross-check (analysis/lockgraph.py).
        self.sanitize_report = sanitize_report
        if sanitize_report is not None and self.sanitizer is None:
            raise ValueError("sanitize_report requires sanitize=True")
        # POST /profile/start|stop (single-flight jax.profiler wrap);
        # None keeps the endpoints disabled — profiling writes device
        # traces to disk, so it must be an explicit operator opt-in.
        self.profiler = ProfileSession(profile_dir) \
            if profile_dir else None
        # Structured one-line-per-request access log (off by default:
        # a busy server must not pay per-request stderr IO unasked).
        self.access_log = bool(access_log)
        self._access_log_file = sys.stderr
        # Batching policy: "continuous" (engine, default), "coalesce"
        # (legacy baseline), "off" (serialize — the A/B floor for
        # benchmarks/bench_serving_load.py).  The old boolean kwarg
        # maps onto the modes it used to select.
        if batching is None:
            batching = ("coalesce" if coalesce else "off") \
                if coalesce is not None else "continuous"
        if batching not in BATCHING_MODES:
            raise ValueError(f"batching must be one of "
                             f"{BATCHING_MODES}; got {batching!r}")
        self.batching = batching
        # Optional speculative-decoding draft: requests opt in with
        # {"speculative": true}; greedy by default (output identical
        # to plain greedy decode), rejection-sampled with temperature
        # (models/generate.generate_speculative).  ``spec_k`` is both
        # the default per-request draft length AND the engine's cap:
        # the spec step program's verify chunk is cap+1 wide for
        # EVERY resident, so the cap bounds the end-of-cache slack
        # engine co-tenants must leave (requests that don't fit, or
        # ask for a bigger k, decode solo — see _note_fallback).
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        from ..models.generate import _check_spec_k

        _check_spec_k(spec_k)
        self.spec_k_default = int(spec_k)
        self.model_name = model_name
        self.max_batch = int(max_batch)
        self.extra_info = info or {}
        # Request lifecycle: the default priority class for requests
        # that don't declare one (validated by SchedulerPolicy below
        # even in engine-less modes), the bounded front-end wait cap
        # (None = unbounded — NOT the default: a wedged engine must
        # shed its waiters, never collect HTTP workers forever), and
        # the drain latch (/drain flips it; /healthz reports 503).
        if default_priority not in PRIORITIES:
            raise ValueError(
                f"default_priority must be one of {PRIORITIES}; "
                f"got {default_priority!r}")
        self.default_priority = default_priority
        if request_timeout_s is not None and request_timeout_s <= 0:
            raise ValueError(f"request_timeout_s must be > 0; got "
                             f"{request_timeout_s}")
        self.request_timeout_s = request_timeout_s
        self.draining = False
        self.drain_rejected = 0     # 503s shed at the drain gate
        # Fair handoff (FairLock): the engine's step loop re-acquires
        # this lock at every boundary, and an unfair lock starves
        # handler-thread device work (wire-fetch admits, /prefill,
        # solo requests) behind it for hundreds of ms.
        self._lock = FairLock() if self.sanitizer is None \
            else self.sanitizer.wrap("device_lock", FairLock())
        # LRU-bounded: the key includes client-controlled sampling
        # values (temperature must stay trace-static — the greedy
        # branch is Python-level control flow), so unbounded caching
        # would let varied traffic grow compiled programs without
        # limit.
        from collections import OrderedDict

        self._fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._fn_cap = 32
        self.requests = 0
        # Continuous-batching engine: decoder-only models only (a
        # seq2seq cache holds computed cross-attention K/V — its
        # decode loop is a different program the slot engine doesn't
        # speak).  Seq2seq falls back to the seed coalescer so
        # concurrent greedy requests still batch, and self.batching
        # (reported by /info) reflects what actually runs.
        self.engine: Optional[DecodeEngine] = None
        if self.batching == "continuous" and hasattr(model, "encode"):
            self.batching = "coalesce"
        if kv_paged and self.batching != "continuous":
            # Paged KV is the engine's storage discipline — there is
            # nothing to page in the coalesce/off solo paths.
            raise ValueError(
                "kv_paged requires the continuous-batching engine "
                f"(batching={self.batching!r}"
                + (" — seq2seq models fall back to coalesce)"
                   if hasattr(model, "encode") else ")"))
        if kv_lazy and not kv_paged:
            raise ValueError(
                "kv_lazy requires kv_paged (lazy growth is a page-"
                "reservation policy; fixed lanes have no pages)")
        if kv_host_spill_bytes < 0:
            raise ValueError(
                f"kv_host_spill_bytes must be >= 0; got "
                f"{kv_host_spill_bytes}")
        if kv_host_spill_bytes and not kv_paged:
            raise ValueError(
                "kv_host_spill_bytes requires kv_paged (the host "
                "tier spills page-pool payloads; legacy prefix "
                "entries already own independent caches)")
        if prefix_fetch and not (kv_paged and kv_host_spill_bytes):
            raise ValueError(
                "prefix_fetch requires kv_paged AND a host spill "
                "budget (--kv-host-spill-bytes): wire-fetched "
                "payloads are host-tier entries — they enter through "
                "the spill machinery and count against its budget")
        if prefix_fetch_timeout_s <= 0:
            raise ValueError(
                f"prefix_fetch_timeout_s must be > 0; got "
                f"{prefix_fetch_timeout_s}")
        # DISAGGREGATED ROLES (docs/SERVING.md "Disaggregated
        # serving"): "both" is today's monolithic replica,
        # byte-for-byte.  "prefill" runs prompt prefill only — it
        # serves /prefill and the /prefix/* wire lanes and rejects
        # /generate with a typed 400, so no decode stream is ever
        # resident and the whole pool/spill budget backs admit-ready
        # prefixes.  "decode" is a full replica expected to pull
        # handed-off KV over the wire-fetch lane (and to degrade to
        # local re-prefill, counted, when a fetch fails).
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}; "
                             f"got {role!r}")
        if role == "prefill" and not (kv_paged and kv_host_spill_bytes):
            raise ValueError(
                "role='prefill' requires kv_paged AND a host spill "
                "budget (--kv-host-spill-bytes): a prefill tier's "
                "only product is admit-ready KV state served over "
                "the /prefix/fetch wire lane, which packs from the "
                "paged pool and the host tier")
        if role == "decode" and not prefix_fetch:
            raise ValueError(
                "role='decode' requires prefix_fetch: a decode tier "
                "admits handed-off prefills through the wire-fetch "
                "lane (it still re-prefills locally, counted, when "
                "a fetch degrades)")
        self.role = role
        # Serving mesh ("tp=4" / MeshSpec / ServingMesh): shard the
        # slot KV pools over the mesh and place params under
        # NamedSharding (serving/meshed.py — the exact layout, so
        # meshed responses are token-bitwise-identical to unmeshed
        # ones per seed).  Params are placed HERE, before the engine
        # and before _split_fns capture self.variables, so every
        # program — engine steps, prefill, solo fallbacks — runs over
        # the same placed tree.
        self.mesh = None
        if mesh is not None:
            from .meshed import MeshError, ServingMesh

            if self.batching != "continuous":
                # MeshError (a ValueError) so the CLI's clean
                # usage-error surface catches it — the seq2seq
                # fallback above can flip batching AFTER the CLI's
                # own pre-check passed.
                raise MeshError(
                    "mesh requires the continuous-batching engine "
                    f"(batching={self.batching!r}"
                    + (" — seq2seq models fall back to coalesce)"
                       if hasattr(model, "encode") else ")"))
            self.mesh = mesh if isinstance(mesh, ServingMesh) \
                else ServingMesh(mesh)
            self.mesh.validate_model(model, "model", n_slots=n_slots)
            if draft_model is not None:
                self.mesh.validate_model(draft_model, "draft model")
            self.variables = variables = \
                self.mesh.place_params(variables)
            if draft_variables is not None:
                self.draft_variables = draft_variables = \
                    self.mesh.place_params(draft_variables)
        if self.batching == "continuous":
            self.engine = DecodeEngine(
                model, variables,
                policy=SchedulerPolicy(
                    n_slots=n_slots, queue_depth=queue_depth,
                    prefill_chunk=prefill_chunk,
                    decode_window=decode_window,
                    default_priority=default_priority,
                    batch_queue_depth=batch_queue_depth,
                    queue_deadline_s=queue_deadline_s,
                    batch_queue_deadline_s=batch_queue_deadline_s,
                    slo_ttft_s=slo_ttft_s,
                    kv_paged=kv_paged,
                    kv_page_tokens=kv_page_tokens,
                    kv_pages=kv_pages,
                    kv_lazy=kv_lazy,
                    spec_k_cap=self.spec_k_default),
                device_lock=self._lock,
                # Engine streams are single-row; share the server's
                # compile cache so a prompt length prefilled via
                # /prefill and via engine admission compiles once.
                prefill_fns=lambda s, first: self._split_fns(
                    1, s, "pfill" if first else "extend", None),
                # Draft model makes speculative requests engine
                # citizens (spec step program, slots.py).
                draft_model=draft_model,
                draft_variables=draft_variables,
                telemetry=self.telemetry,
                sentinel=self.recompile,
                mesh=self.mesh,
                faults=self.faults)
        self._coalescer = RequestCoalescer(self) \
            if self.batching == "coalesce" else None
        self.coalesced_batches = 0
        self.coalesced_requests = 0
        # /metrics counters.  _stats_lock guards every tally mutated
        # from handler threads (requests/hits/errors/latency/tokens) —
        # NEVER the device lock, so bumping a counter can't queue a
        # finished request behind in-flight device work; reads are
        # unlocked, consistent enough for monotonic counters.
        self._stats_lock = threading.Lock() \
            if self.sanitizer is None \
            else self.sanitizer.wrap("_stats_lock")
        self.errors = 0
        # Requests that fell back to the solo path, keyed by request
        # kind: {"reason": ..., "count": n}.  Surfaced in /info's
        # routing report; the reason is logged ONCE per kind.
        self.solo_fallbacks: Dict[str, Dict[str, Any]] = {}
        self._lat_sum = 0.0
        self._lat_count = 0
        self._tokens_out = 0
        # Per-request phase breakdown (queue -> prefill -> decode)
        # summed across engine AND solo requests: solo requests spend
        # their "queue" phase waiting on the device lock and have no
        # separate prefill phase (it is fused into their program).
        self._queue_s_sum = 0.0
        self._prefill_s_sum = 0.0
        self._decode_s_sum = 0.0
        self._breakdown_count = 0
        # PREFIX CACHE: stored prompt prefills in a RADIX index
        # (serving/radix.py — O(prompt) longest-match lookup however
        # many entries, replacing the seed's O(entries) linear scan),
        # LRU-bounded.  A request whose prompt extends a stored entry
        # pays prefill only for the suffix (models/generate.prefill's
        # extension contract).  Entry storage depends on the engine:
        # LEGACY (fixed-lane / engine-less): each entry holds its own
        # contiguous B=1 cache, O(max_position) device memory.
        # PAGED (kv_paged): single-row entries hold POOL PAGES — a
        # stored system prompt is prefilled once and its pages are
        # shared (refcounted, copy-on-write) by every extension entry
        # and every resident slot that hits it, so admission of a hit
        # costs only the divergent suffix.  prefix_cache=0 disables.
        self.prefix_cache_size = int(prefix_cache)
        if not hasattr(model, "encode"):
            self._prefix_enabled = self.prefix_cache_size > 0
        else:
            self._prefix_enabled = False  # seq2seq: encoder != prefix
        # The CONFIGURED state, captured once: the degradation
        # ladder may flip _prefix_enabled off at runtime, and engine
        # recovery restores it to exactly this — never beyond what
        # construction decided.
        self._prefix_configured = self._prefix_enabled
        self._prefix = RadixPrefixIndex(max(1, self.prefix_cache_size))
        self._prefix_lock = threading.Lock() \
            if self.sanitizer is None \
            else self.sanitizer.wrap("_prefix_lock")
        self.prefix_hits = 0
        # Prefix-reuse hit-token counter: prompt tokens served from a
        # stored prefill instead of fresh prefill work — the measure
        # the shared-prefix bench leg asserts on.
        self.prefix_hit_tokens = 0
        self._prefix_store_skips = 0   # paged stores dropped for
        #                                pool pressure (logged once)
        # Degradation ladder (docs/SERVING.md "Fault tolerance"): a
        # prefix-store ERROR (real, or the ``prefix_store`` fault
        # site) disables the store with a counter instead of failing
        # the request — the cache is an optimization, and a broken
        # optimization must cost hit-rate, never availability.
        self._prefix_store_errors = 0
        self.kv_paged = bool(self.engine is not None
                             and self.engine.paged)
        self.kv_lazy = bool(self.kv_paged and kv_lazy)
        # HOST-RAM SPILL TIER for the prefix store (PR 12, tentpole
        # b): a byte budget > 0 makes page-pressure eviction DEMOTE a
        # paged entry — payload gathered to host buffers by the
        # sanctioned spill helper — instead of dropping it, so the
        # shareable-prefix working set is bounded by host RAM, not
        # device pages.  A host-tier hit re-materializes via
        # device_put (+ opportunistic promotion back to pages).  All
        # counters under _stats_lock; _spill_stats() is the ONE dict
        # /metrics and /info render (no drift).
        self.kv_host_spill_bytes = int(kv_host_spill_bytes)
        self._host_bytes = 0
        self._host_entries = 0
        self._host_spills_total = 0
        self._host_dropped_total = 0    # budget evictions + oversize
        self._remat_hits_total = 0
        self._remat_bytes_total = 0
        self._promotions_total = 0
        # FLEET PREFIX CACHE (PR 16): the host tier goes on the wire.
        # ``prefix_fetch`` arms the CLIENT half (an affinity miss
        # with a router-supplied ``prefix_hint`` fetches the holder's
        # spilled payload instead of re-prefilling, gated by the
        # PrefixFetchPolicy cost curve); the SERVING half — the
        # /prefix/fetch|ingest|index|evict|handoff endpoints — is
        # always mounted on paged servers so a drain handoff or a
        # peer's fetch needs no arming on the holder.  All counters
        # under _stats_lock; _spill_stats() renders them on BOTH
        # /metrics and /info (no drift).  Every failure class on
        # these paths degrades to a typed re-prefill — never a
        # request failure.
        self.prefix_fetch = bool(prefix_fetch)
        self.prefix_fetch_timeout_s = float(prefix_fetch_timeout_s)
        self.fetch_policy = prefix_fetch_policy \
            if prefix_fetch_policy is not None else PrefixFetchPolicy()
        self._fetch_attempts_total = 0
        self._fetch_hits_total = 0
        self._fetch_bytes_total = 0
        self._fetch_failed: Dict[str, int] = {}
        self._ingest_total = 0
        self._ingest_rejected_total = 0
        self._handoff_entries_total = 0
        self._handoff_bytes_total = 0
        self._handoff_failed_total = 0
        self._evict_hints_total = 0
        if self.kv_paged:
            # Page-pressure relief: when an admit-ready stream is
            # blocked on free pages, the engine asks us to evict
            # stored-but-idle prefix entries (LRU; pages shared with
            # residents survive via their refcounts) — spilling their
            # payloads to the host tier first when it is enabled.
            self.engine.page_reclaim = self._reclaim_prefix_pages
        # FLIGHT RECORDER (serving/profiling.py), off by default:
        # --profile-every N --profile-steps K periodically wraps K
        # decode-step boundaries in a jax.profiler window, analyzes
        # the dump off-thread (analysis/xprof.py), and publishes
        # trace-TRUE attribution — collective/transfer/host-gap/
        # device-busy shares + serving MFU — as /metrics gauges, the
        # /info "profiling" block, and GET /profile/report.  One
        # published record behind all three surfaces, so they can
        # never drift; shares the manual endpoints' ProfileSession,
        # so recorder windows and POST /profile/start are single-
        # flight against each other.
        self.recorder = None
        if profile_every:
            if profile_every < 0:
                raise ValueError(
                    f"profile_every must be >= 0; got "
                    f"{profile_every}")
            if self.profiler is None:
                raise ValueError(
                    "profile_every needs profile_dir (the flight "
                    "recorder writes jax.profiler windows there)")
            if self.engine is None:
                raise ValueError(
                    "profile_every requires the continuous-batching "
                    f"engine (batching={self.batching!r}) — the "
                    "recorder windows decode-step boundaries")
            from .profiling import (FlightRecorder,
                                    decode_flops_per_token,
                                    detect_peak_flops)

            cfg = getattr(model, "cfg", None)
            peak = detect_peak_flops()
            self.recorder = FlightRecorder(
                self.profiler, every=profile_every,
                steps=profile_steps, telemetry=self.telemetry,
                flops_fn=(lambda pos: decode_flops_per_token(
                    cfg, pos)) if cfg is not None else None,
                peak_flops=peak["peak_flops"],
                peak_flops_source=peak["peak_flops_source"],
                n_devices=self.mesh.n_devices
                if self.mesh is not None else 1,
                position_probe=self.engine.mean_resident_position)
            self.engine.recorder = self.recorder
        # REQUEST-SCOPED DEBUGGABILITY (serving/debug.py).  The
        # history ring answers "what happened to THIS request"
        # (GET /requests/<id>); the engine writes the full causal
        # record on every terminal path, and the front-end writes a
        # minimal one for requests the engine never saw (validation
        # 400s, solo paths, drain 503s) — engine records supersede.
        # request_history=0 disables the whole layer (one attribute
        # check on the engine's terminal paths, same off-switch
        # contract as the trace ring).
        self.history = RequestHistory(request_history)
        if self.engine is not None:
            self.engine.history = self.history
        # TAIL-LATENCY FORENSICS (serving/forensics.py), ON by
        # default: the phase accumulator behind the per-phase
        # /metrics families and the anomaly sentry behind
        # GET /anomalies.  The engine's terminal paths feed it each
        # request's phase ledger; solo paths feed it from the
        # handler.  ``forensics=False`` removes the whole layer (the
        # bench's forensics_overhead off arm); ``forensics_dir``
        # arms on-disk anomaly bundles (StallWatchdog's one-shot
        # discipline).
        self.forensics: Optional[ForensicsCore] = None
        if forensics:
            self.forensics = ForensicsCore(
                window=sentry_window,
                baseline_windows=sentry_baseline_windows,
                out_dir=forensics_dir,
                snapshot_fn=(
                    (lambda: self.engine.build_debug_snapshot(
                        forced=True))
                    if self.engine is not None else None),
                trace_tail_fn=lambda: self.telemetry.events()[-256:],
                record_fn=self.history.get)
            if self.engine is not None:
                self.engine.forensics = self.forensics
        # STALL WATCHDOG (opt-in via --stall-timeout): declares a
        # stall when work exists but no step boundary completes, and
        # writes a one-shot diagnostic bundle (forced state snapshot
        # + trace tail + thread stacks) before anyone restarts the
        # evidence away.  Engine-only: solo paths have no step
        # boundary to watch — their stall surface is the bounded
        # front-end wait (request_timeout_s).
        self.watchdog = None
        if stall_timeout_s is not None:
            if self.engine is None:
                raise ValueError(
                    "stall_timeout_s requires the continuous-"
                    f"batching engine (batching={self.batching!r}) — "
                    "the watchdog monitors decode-step boundaries")
            self.watchdog = StallWatchdog(
                self.engine, self.telemetry,
                timeout_s=stall_timeout_s, out_dir=stall_dir,
                queue_factor=stall_queue_factor,
                extra_state=self._watchdog_extra_state)
            self.watchdog.start()
        # ENGINE SUPERVISOR (serving/recovery.py), ON by default for
        # engine-backed servers: an exception escaping the engine's
        # scheduling layer no longer fails every in-flight request —
        # the supervisor requeues everything for token-identical
        # resume, rebuilds the pools (zero recompiles), and restarts
        # the loop with bounded backoff; a crash STORM trips the
        # circuit breaker instead (healthz 503 ``engine_down``, new
        # submits shed — fail fast, never hang).  ``supervise=False``
        # keeps the legacy fail-everything crash behavior.
        self.supervisor = None
        if self.engine is not None and supervise:
            self.supervisor = EngineSupervisor(self.engine)
            self.supervisor.add_recovery_hook(
                self._on_engine_recovery)

    def close(self) -> None:
        """Stop the engine loop thread (idempotent) and end any
        in-flight profiler trace (recorder window or manual)."""
        if self.watchdog is not None:
            self.watchdog.close()
        if self.engine is not None:
            self.engine.close()
        if self.recorder is not None:
            self.recorder.close()
        if self.profiler is not None:
            self.profiler.close()
        if self.sanitizer is not None \
                and self.sanitize_report is not None:
            # Written LAST: the engine drain above is the final
            # source of acquisitions, so the dump is the complete
            # observed graph for this server's lifetime.
            with open(self.sanitize_report, "w",
                      encoding="utf-8") as fh:
                json.dump(self.sanitizer.stats(), fh, indent=1,
                          sort_keys=True)
                fh.write("\n")

    def _exact(self):
        """Serving-exact trace context for the server's own device
        sections (solo programs and prefill trace over the mesh's
        column-sharded params; the exact constraint mode keeps their
        output bitwise-identical to unmeshed).  No-op unmeshed."""
        return self.mesh.exact() if self.mesh is not None \
            else contextlib.nullcontext()

    # -- request lifecycle ----------------------------------------------

    def drain(self) -> Dict[str, Any]:
        """POST /drain: stop admitting (every path — engine, solo,
        coalesce — sheds new requests with 503 ``draining``), let
        in-flight work finish, and turn /healthz readiness off so a
        router/load-balancer stops sending traffic here.  Idempotent;
        returns the in-flight snapshot so an orchestrator can poll
        until it hits zero before killing the process."""
        self.draining = True
        if self.engine is not None:
            self.engine.drain()
        return self.drain_status()

    def drain_status(self) -> Dict[str, Any]:
        es = self.engine.stats() if self.engine is not None else {}
        return {"draining": self.draining,
                "drain_rejected": self.drain_rejected,
                "slots_active": es.get("slots_active", 0),
                "queue_len": es.get("queue_len", 0)}

    # -- request-scoped debuggability -----------------------------------

    def debug_state(self) -> Dict[str, Any]:
        """``GET /debug/state``: a CONSISTENT snapshot of engine
        internals plus the server-level lifecycle surface.  The
        engine half is the snapshot it published at its most recent
        step boundary (SnapshotBoard — built on the engine thread,
        outside the device lock, so it is internally consistent and
        this handler can never block behind a wedged device call:
        the SNAPSHOT-LOCK contract, docs/DESIGN.md)."""
        now = time.perf_counter()
        out: Dict[str, Any] = {
            "model": self.model_name,
            "batching": self.batching,
            "draining": self.draining,
            "history": self.history.stats(),
        }
        if self.engine is not None:
            snap = self.engine.debug_board.latest()
            if snap is not None:
                snap["age_s"] = round(max(0.0, now - snap["t"]), 3)
                del snap["t"]   # perf_counter origin: meaningless
                #                 outside the process; age_s is the
                #                 consumable form
            out["engine"] = snap
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.status()
        if self.sanitizer is not None:
            # The lock-sanitizer's acquisition graph (edges +
            # violations) when armed — the bundle's deadlock
            # evidence, live.
            out["sanitizer"] = self.sanitizer.stats()
        return out

    def _watchdog_extra_state(self) -> Dict[str, Any]:
        """Server-level state folded into the stall bundle's
        snapshot (the watchdog has no back-reference to us)."""
        return {
            "draining": self.draining,
            "requests": self.requests,
            "errors": self.errors,
            "history": self.history.stats(),
            # Degradation-ladder state: a stall bundle from a
            # recovery storm should show whether the prefix store
            # disabled itself along the way.
            "prefix_enabled": self._prefix_enabled,
            "prefix_store_errors": self._prefix_store_errors,
            **({"sanitizer": self.sanitizer.stats()}
               if self.sanitizer is not None else {}),
        }

    def record_front(self, rid: Optional[str], path: str,
                     status: int, req, resp) -> None:
        """Minimal front-end history record for a request the ENGINE
        never recorded — validation 400s, drain/queue sheds, solo and
        coalesce paths.  Engine-path records are written by the
        engine itself with the full causal timeline; this only fills
        the gap (RequestHistory.record_front never overwrites)."""
        if rid is None or not self.history.enabled:
            return
        # Mirror the handler's error->HTTP mapping back into the
        # engine's terminal-status vocabulary, so GET /requests?
        # status=shed finds queue-full/drain sheds the engine never
        # saw and a record never disagrees with its trace instants.
        front_status = {200: "complete", 429: "shed", 503: "shed",
                        504: "expired", 499: "cancelled"}.get(
                            int(status), "failed")
        rec: Dict[str, Any] = {
            "request_id": rid, "t": round(time.time(), 3),
            "path": path, "http_status": int(status),
            "status": front_status}
        if isinstance(req, dict):
            rec["kind"] = self._request_kind(req, path)
        if isinstance(resp, dict):
            if resp.get("error"):
                rec["error"] = str(resp["error"])[:300]
            if resp.get("reason"):
                rec["reason"] = resp["reason"]
            if "wall_s" in resp:
                rec["wall_s"] = resp["wall_s"]
        self.history.record_front(rec)

    def _check_not_draining(self) -> None:
        if self.draining:
            # Counted HERE (the shed happens at validation, before
            # the engine ever sees the request) so /metrics shows
            # drain-time 503s instead of staying flat while the
            # access log streams them.
            with self._stats_lock:
                self.drain_rejected += 1
            raise ShedError(
                "server is draining: finishing in-flight requests, "
                "admitting none", reason="draining")

    def _wait_group(self, group, cancel_check=None) -> None:
        """Bounded wait for an engine group — the front-end half of
        the lifecycle contract.  Replaces the old untimed
        ``group.event.wait()``, which held an HTTP worker until
        engine drain if the engine ever wedged.  Three give-up paths,
        all delivered to the engine as a boundary cancel first:

        - ``cancel_check`` (client-disconnect probe) fires ->
          :class:`RequestCancelled` (499; nobody is listening);
        - the request's own deadline passes -> the engine sweep
          normally delivers :class:`DeadlineExceeded` itself, but a
          front-end check backstops a wedged engine;
        - ``request_timeout_s`` elapses with no terminal state ->
          :class:`ShedError` (503 ``request_timeout``).

        Raising without waiting for the engine's acknowledgement is
        safe: the group is flagged, its slots free at the engine's
        next boundary, and a late completion writes into state nobody
        reads."""
        cap = None
        if self.request_timeout_s is not None:
            cap = group.t_submit + self.request_timeout_s
        while not group.event.wait(0.1):
            now = time.perf_counter()
            if cancel_check is not None and cancel_check():
                err = RequestCancelled(
                    "client disconnected; request cancelled")
                self.engine.cancel(group, err)
                raise err
            if group.deadline is not None and now > group.deadline:
                err = DeadlineExceeded(
                    f"deadline exceeded after "
                    f"{now - group.t_submit:.3f}s "
                    f"({group.status_phase()})")
                self.engine.cancel(group, err)
                raise err
            if cap is not None and now > cap:
                err = ShedError(
                    f"request exceeded the server request timeout "
                    f"({self.request_timeout_s}s) without reaching a "
                    f"terminal state; shedding the waiter",
                    reason="request_timeout")
                self.engine.cancel(group, err)
                raise err
        if group.error is not None:
            raise group.error

    def log_access(self, method: str, path: str, status: int,
                   req, resp, dt: float,
                   rid: Optional[str] = None) -> None:
        """One structured line per request (the satellite fix for the
        silent ``log_message`` no-op: before this, failed requests
        vanished entirely).  Defensive about ``req`` — it may be
        unparsed garbage on 400s — and writes a single JSON object
        per line so log pipelines need no multi-line stitching."""
        if not self.access_log:
            return
        rec: Dict[str, Any] = {
            "t": round(time.time(), 3), "method": method,
            "path": path, "status": int(status),
            "ms": round(1e3 * dt, 3)}
        if rid is not None:
            # The correlation key: grep the access log, the trace
            # ring, and GET /requests/<id> by the same string.
            rec["request_id"] = rid
        if isinstance(resp, dict):
            # Engine-path provenance (slot id, preempt/resume
            # counts): a resumed request reads differently from a
            # straight-through one in the log.
            for k in ("slot", "preempts", "resumes"):
                if k in resp:
                    rec[k] = resp[k]
        if isinstance(req, dict):
            rec["kind"] = self._request_kind(req, path)
            rows = req.get("prompt")
            if isinstance(rows, list) and rows:
                rec["rows"] = len(rows) \
                    if isinstance(rows[0], list) else 1
        if isinstance(resp, dict):
            if status == 200 and "new_tokens" in resp:
                rec["new_tokens"] = sum(
                    len(r) for r in resp["new_tokens"]
                    if isinstance(r, list))
            err = resp.get("error")
            if err:
                rec["error"] = str(err)[:200]
        try:
            print(json.dumps(rec), file=self._access_log_file,
                  flush=True)
        except Exception:
            pass  # logging must never fail a request

    @staticmethod
    def _request_kind(req: Dict[str, Any], path: str) -> str:
        if path == "/prefill":
            return "prefill"
        if req.get("speculative") is True:
            return "speculative"
        beams = req.get("num_beams")
        if isinstance(beams, int) and not isinstance(beams, bool) \
                and beams > 1:
            return "beam"
        temp = req.get("temperature", 0)
        if isinstance(temp, (int, float)) \
                and not isinstance(temp, bool) and temp > 0:
            return "sampled"
        return "greedy"

    def _note_fallback(self, kind: str, reason: str) -> None:
        """A request class fell back to the solo decode path: count
        it under its kind and log the reason ONCE per kind (a busy
        server must not spam stderr per request).  /info surfaces the
        table, so a silently-solo workload is diagnosable."""
        with self._stats_lock:
            fb = self.solo_fallbacks.get(kind)
            first = fb is None
            if first:
                self.solo_fallbacks[kind] = {"reason": reason,
                                             "count": 1}
            else:
                fb["count"] += 1
        if first:
            print(f"# serving: {kind} requests take the solo path — "
                  f"{reason}", file=sys.stderr)

    def _note_breakdown(self, queue_s: float, prefill_s: float,
                        decode_s: float) -> None:
        with self._stats_lock:
            self._queue_s_sum += queue_s
            self._prefill_s_sum += prefill_s
            self._decode_s_sum += decode_s
            self._breakdown_count += 1

    # -- compile cache --------------------------------------------------

    def _fn(self, key):
        import jax

        from ..models import generate as G

        def build():
            kind, b, p_len, new, temp, top_k, top_p, eos, beams, \
                chunk = key
            if kind == "beam":
                return jax.jit(lambda toks, rng: G.generate_beam(
                    self.model, self.variables, toks,
                    max_new_tokens=new, num_beams=beams, eos_id=eos,
                    prefill_chunk=chunk))
            if kind == "sample_pos":
                # Position-keyed sampled solo path: the shaping params
                # are RUN-TIME arguments (traced scalars), so every
                # sampled (temperature, top_k, top_p, seed) combo of
                # one shape shares a single compiled program — and the
                # math is the same _sample_positional_row the engine's
                # slot step runs.
                return jax.jit(
                    lambda toks, keys, temp, tk, tp:
                    G.generate_positional(
                        self.model, self.variables, toks,
                        max_new_tokens=new, keys=keys,
                        temperature=temp, top_k=tk, top_p=tp,
                        eos_id=eos, prefill_chunk=chunk))
            if kind == "spec":
                k = beams  # slot reused for the draft length
                return jax.jit(lambda toks, rng: G.generate_speculative(
                    self.model, self.variables, self.draft_model,
                    self.draft_variables, toks, max_new_tokens=new,
                    k=k, eos_id=eos, prefill_chunk=chunk,
                    temperature=temp, top_k=top_k, top_p=top_p,
                    rng=rng if temp != 0.0 else None))
            if kind == "spec_pos":
                # sampled speculative solo under the position-keyed
                # schedule — the reference the engine's spec slots
                # are pinned against, so solo and engine agree
                # token-for-token per seed
                k = beams  # slot reused for the draft length
                return jax.jit(
                    lambda toks, keys: G.generate_speculative(
                        self.model, self.variables, self.draft_model,
                        self.draft_variables, toks,
                        max_new_tokens=new, k=k, eos_id=eos,
                        prefill_chunk=chunk, temperature=temp,
                        top_k=top_k, top_p=top_p, keys=keys))
            return jax.jit(lambda toks, rng: G.generate(
                self.model, self.variables, toks, max_new_tokens=new,
                temperature=temp, top_k=top_k, top_p=top_p,
                eos_id=eos, rng=rng, prefill_chunk=chunk))

        return lru_get(self._fns, key, self._fn_cap, build,
                       sentinel=self.recompile,
                       kind=f"server:{key[0]}")

    # -- prefix cache ----------------------------------------------------

    def _split_fns(self, b: int, p_or_s: int, kind: str, chunk,
                   new=None, temp=None, top_k=None, top_p=None,
                   eos=None):
        """Jitted split programs for the prefix-cache path:
        ``pfill``/``extend`` produce (logits, cache); ``cont`` decodes
        from a cache.  Cached in the same LRU as the fused programs."""
        import jax

        from ..models import generate as G

        # "cont"/"cont_pos" do not depend on chunk — keying them would
        # compile duplicate identical decode programs per chunk value.
        key = (kind, b, p_or_s, new, temp, top_k, top_p, eos, None,
               chunk if kind not in ("cont", "cont_pos") else None)

        def build():
            if kind == "pfill":
                return jax.jit(lambda toks: G.prefill(
                    self.model, self.variables, toks, chunk=chunk))
            if kind == "extend":
                return jax.jit(lambda cache, toks, pos: G.prefill(
                    self.model, self.variables, toks, chunk=chunk,
                    cache=cache, position=pos))
            if kind == "cont_pos":
                # position-keyed sampled continue (prefix-cache hits
                # that stay solo): one program per shape, shaping
                # params at run time — mirrors "sample_pos"
                return jax.jit(
                    lambda cache, logits, pos, keys, temp, tk, tp:
                    G.generate_continue_positional(
                        self.model, self.variables, cache, logits,
                        pos, max_new_tokens=new, keys=keys,
                        temperature=temp, top_k=tk, top_p=tp,
                        eos_id=eos, _validated=True))
            return jax.jit(lambda cache, logits, pos, rng:
                           G.generate_continue(
                               self.model, self.variables, cache,
                               logits, pos, max_new_tokens=new,
                               temperature=temp, top_k=top_k,
                               top_p=top_p, rng=rng, eos_id=eos,
                               _validated=True))

        return lru_get(self._fns, key, self._fn_cap, build,
                       sentinel=self.recompile,
                       kind=f"server:{kind}")

    # -- fault tolerance: prefix-store degradation + engine recovery ----

    def _note_prefix_error(self, where: str) -> None:
        """One prefix-store failure: count it and DISABLE the store
        (lookups miss, stores skip) — requests keep flowing without
        prefix reuse instead of 500ing on a broken cache.  The
        counter + the disabled flag surface in /info and /metrics so
        the degradation is an alert, not a mystery slowdown."""
        with self._stats_lock:
            self._prefix_store_errors += 1
            first = self._prefix_enabled
            self._prefix_enabled = False
        if first:
            print(f"# serving: prefix store DISABLED after an error "
                  f"in {where} — requests continue without prefix "
                  f"reuse (degradation ladder; counted in /info "
                  f"prefix_store_errors)", file=sys.stderr)

    def _prefix_lookup_safe(self, toks: np.ndarray
                            ) -> Optional[PrefixHit]:
        """Contained prefix lookup: an error (injected via the
        ``prefix_store`` fault site, or real — a corrupt trie, a
        failed page materialization) degrades to a MISS and disables
        the store; the request pays full prefill and succeeds."""
        if not self._prefix_enabled:
            return None
        try:
            if self.faults is not None:
                self.faults.check("prefix_store")
            return self._prefix_lookup(toks)
        except Exception:
            self._note_prefix_error("lookup")
            return None

    def _prefix_store_safe(self, toks, logits, cache, *,
                           hot: bool = True) -> None:
        """Contained prefix store: same degradation contract as the
        lookup — a failing store must never fail the request whose
        prefill it was opportunistically caching."""
        if not self._prefix_enabled:
            return
        try:
            if self.faults is not None:
                self.faults.check("prefix_store")
            self._prefix_store(toks, logits, cache, hot=hot)
        except Exception:
            self._note_prefix_error("store")

    def _on_engine_recovery(self) -> None:
        """EngineSupervisor recovery hook, run after the slot/page
        pool rebuild and before the loop restart.  PAGED prefix
        entries hold page ids into the pool that was just reset —
        their payloads are gone, so the whole index is flushed BY
        REFERENCE (no unpins: the fresh pool's accounting starts
        all-free, and unpinning stale ids into it would corrupt the
        new refcounts).  Legacy contiguous entries survive crashes
        (they own independent caches), so engine-less storage is
        kept."""
        if not self.kv_paged:
            return
        displaced = []
        with self._prefix_lock:
            # HOST-TIER entries SURVIVE the flush: their payloads are
            # host buffers referencing no device state — exactly the
            # epoch contract (stale device ids die with the pool
            # generation; host bytes don't).  Re-stored in eviction
            # order, coldest first, so recency survives too.
            keep = [(t, p) for t, p in self._prefix.entries()
                    if isinstance(p, _SpilledPrefix)]
            self._prefix = RadixPrefixIndex(
                max(1, self.prefix_cache_size))
            for t, p in keep:
                displaced += self._prefix.store(t, p)
        if displaced:
            self._free_displaced(displaced)
        # A store error during the crash window (e.g. a pin racing
        # the pool reset) may have tripped the degradation ladder;
        # the flush just removed whatever was broken, so a
        # config-enabled store comes back up.  (Counted errors stay
        # counted — the episode remains visible in /info.)
        if self._prefix_configured:
            with self._stats_lock:
                self._prefix_enabled = True

    def _prefix_lookup(self, toks: np.ndarray
                       ) -> Optional[PrefixHit]:
        """Longest stored entry whose prompt is a prefix of ``toks``
        (same batch) via one radix walk.  Paged entries are PINNED
        under the prefix lock (so eviction can't free their pages
        mid-flight), materialized into a contiguous cache under the
        device lock, and returned with their still-pinned FULL-page
        ids — the engine path maps those read-only into the admitted
        slot's table; every other outcome must unpin them
        (:class:`PrefixHit`)."""
        with self._prefix_lock:
            hit = self._prefix.lookup(toks)
            if hit is None:
                return None
            ent_toks, payload = hit
            pc = ent_toks.shape[1]
            if isinstance(payload, _SpilledPrefix):
                # Host-tier hit: the payload (immutable host arrays)
                # is safe to carry out of the lock; re-materialize —
                # and opportunistically promote — outside it.
                spilled = payload
            elif not isinstance(payload, _PagedPrefix):
                logits, cache = payload
                return PrefixHit(pc, logits, cache, ())
            else:
                spilled = None
            if spilled is None:
                # Pin while still under the prefix lock: a concurrent
                # eviction between lookup and pin could free the
                # pages.  (Lock order: _prefix_lock > _page_lock.)
                # The returned pool epoch rides the pins to the
                # engine: a crash-recovery rebuild between here and
                # admission invalidates them instead of corrupting
                # fresh counts.
                pin_epoch = self.engine.slots.pin(payload.pages)
        if spilled is not None:
            return self._rematerialize_hit(ent_toks, spilled, pc)
        try:
            with self._lock:
                if self.engine.slots.epoch != pin_epoch:
                    # Pool rebuilt since the pin (recovery holds
                    # this same lock for the rebuild, so the check
                    # cannot race it): the ids are dead — a miss.
                    return None
                cache = self.engine.slots.materialize(payload.pages,
                                                      pc)
        except BaseException:
            if self.engine.slots.epoch != pin_epoch:
                # Crash recovery rebuilt the pool mid-materialize:
                # the failure is the rebuild's, not the store's — a
                # MISS, not an error (counting it would disable the
                # store the recovery hook just flushed clean).  The
                # pins died with the old generation (by reference).
                return None
            # A failed materialization (compile error, device OOM)
            # must not leak the pins — repeated failing hits would
            # otherwise walk the free pool down to permanent
            # kv_pages sheds.
            self.engine.slots.unpin(payload.pages, epoch=pin_epoch)
            raise
        if self.engine.slots.epoch != pin_epoch:
            # Pool rebuilt while we gathered: the gather itself read
            # pre-rebuild content (live device buffers), but the
            # page ids now name OTHER requests' KV in the fresh
            # accounting — drop the hit (miss; pins die by
            # reference) rather than share poisoned pages.
            return None
        # Keep pins only on the FULL pages (the shareable ones — the
        # partial tail page's content rides the materialized cache
        # and is rewritten privately by the admitted slot).
        n_full = pc // self.engine.slots.page_tokens
        pins = PagePins(payload.pages[:n_full], pin_epoch)
        if payload.pages[n_full:]:
            self.engine.slots.unpin(payload.pages[n_full:],
                                    epoch=pin_epoch)
        return PrefixHit(pc, payload.logits, cache, pins)

    def _cache_template(self):
        """ABSTRACT cache pytree (``ShapeDtypeStruct`` leaves) for
        cold-pool shaping — the same shape probe
        ``models.generate.init_cache`` uses, minus the zeros: the
        classifier only reads paths/shapes/dtypes, so nothing is
        allocated or computed here."""
        import jax
        import jax.numpy as jnp

        tokens = jnp.zeros((1, 1), jnp.int32)
        shapes = jax.eval_shape(
            # Shape probe under eval_shape (nothing is ever drawn
            # from this key).  # ptpu: ignore[RNG-DET]
            lambda: self.model.init(jax.random.PRNGKey(0), tokens,
                                    decode=True, decode_position=0))
        return shapes["cache"]

    def _rematerialize_hit(self, ent_toks, payload: "_SpilledPrefix",
                           pc: int) -> PrefixHit:
        """HOST-TIER hit: ``device_put`` the spilled leaves back into
        a contiguous cache (manager.rematerialize — the sanctioned
        host->device helper) and opportunistically PROMOTE the entry
        back to device pages so subsequent hits — and co-resident
        slots — share them copy-on-write again.  Promotion is best-
        effort: a tight pool (the very pressure that spilled the
        entry) just serves the hit from the contiguous cache with no
        shared pages.  Runs on a handler thread with no locks held;
        errors propagate to _prefix_lookup_safe's degradation
        ladder."""
        mgr = self.engine.slots
        with self._lock:
            if not mgr.shaped:
                # Cold pool: this entry arrived over the wire (fetch
                # or drain handoff) BEFORE this replica's first
                # prefill shaped the page pool — a freshly restarted
                # drain successor hits exactly this.  Shape it from
                # an abstract template instead of failing the hit.
                mgr.ensure_shaped(self._cache_template())
            cache = mgr.rematerialize(payload.leaves, pc)
        with self._stats_lock:
            self._remat_hits_total += 1
            self._remat_bytes_total += payload.nbytes
        pins = ()
        ids, ep = mgr.reserve_with_epoch(mgr.pages_needed(pc))
        if ids:
            promoted = False
            try:
                with self._lock:
                    # Epoch re-check INSIDE the device lock, like the
                    # paged store's scatter: recovery rebuilds the
                    # pool under this lock, so a dead-generation
                    # scatter cannot interleave.
                    if mgr.epoch == ep:
                        mgr.scatter_cache(cache, ids)
                        promoted = True
            except Exception:
                promoted = False    # promotion is an optimization
            if promoted:
                new_payload = _PagedPrefix(ids, pc, payload.logits)
                with self._prefix_lock:
                    if self._prefix.set_payload(ent_toks, new_payload,
                                                expect=payload):
                        # This hit maps the promoted FULL pages
                        # read-only, exactly like a device-tier hit;
                        # pin under the prefix lock so an eviction
                        # cannot race the mapping.
                        n_full = pc // mgr.page_tokens
                        pin_epoch = mgr.pin(ids[:n_full]) \
                            if n_full else ep
                        pins = PagePins(ids[:n_full], pin_epoch)
                        promoted_entry = True
                    else:
                        promoted_entry = False
                if promoted_entry:
                    with self._stats_lock:
                        self._host_bytes -= payload.nbytes
                        self._host_entries -= 1
                        self._promotions_total += 1
                else:
                    # Entry changed under us: abandon the promotion
                    # (dead-generation ids drop by reference).
                    mgr.unpin(ids, epoch=ep)
            else:
                mgr.unpin(ids, epoch=ep)
        return PrefixHit(pc, payload.logits, cache, pins,
                         source="host")

    def _unpin_prefix(self, pins) -> None:
        if pins:
            self.engine.slots.unpin(
                pins, epoch=getattr(pins, "epoch", None))

    def _free_displaced(self, displaced) -> None:
        """Release payloads the radix index displaced (overwrites and
        LRU evictions): paged entries drop their page references —
        pages shared by a child entry or a resident slot stay alive
        under the remaining refcounts ("evict leaf pages first, never
        a page with refcount > 1" falls out of the accounting) —
        and host-tier entries leave the spill byte accounting."""
        for _toks, payload in displaced:
            if isinstance(payload, _PagedPrefix):
                self.engine.slots.unpin(payload.pages)
            elif isinstance(payload, _SpilledPrefix):
                with self._stats_lock:
                    self._host_bytes -= payload.nbytes
                    self._host_entries -= 1
                    self._host_dropped_total += 1

    def _spill_entry(self, toks, payload) -> bool:
        """Demote one device-tier entry to the HOST tier: pin its
        pages, gather the payload to host buffers (the sanctioned
        ``spill_pages`` helper, under the device lock), swap the
        entry's payload in place, and release the entry's page
        references — the pages free (to the extent nothing else
        shares them) while the CONTENT survives in host RAM.
        Returns False when the entry must be dropped instead (spill
        failed, over budget, or the entry changed under us)."""
        mgr = self.engine.slots
        with self._prefix_lock:
            # Pin under the prefix lock (same discipline as the
            # lookup): eviction elsewhere cannot free the pages
            # while we gather.  Entry may already be gone/changed —
            # the identity-guarded no-op swap is the O(prompt)
            # presence check (same primitive the drop path uses).
            if not self._prefix.set_payload(toks, payload,
                                            expect=payload):
                return True     # someone else dealt with it
            pin_epoch = mgr.pin(payload.pages)
        try:
            with self._lock:
                if mgr.epoch != pin_epoch:
                    # Pool rebuilt (crash recovery): pins and pages
                    # are dead by reference; the recovery flush owns
                    # the index.
                    return True
                host = mgr.spill_pages(payload.pages,
                                       payload.n_tokens)
                import jax

                logits_host = np.asarray(
                    jax.device_get(payload.logits))
        except Exception:
            mgr.unpin(payload.pages, epoch=pin_epoch)
            return False
        spilled = _SpilledPrefix(host, payload.n_tokens, logits_host)
        if spilled.nbytes > self.kv_host_spill_bytes:
            mgr.unpin(payload.pages, epoch=pin_epoch)
            with self._stats_lock:
                self._host_dropped_total += 1
            return False
        with self._prefix_lock:
            swapped = self._prefix.set_payload(toks, spilled,
                                               expect=payload)
        mgr.unpin(payload.pages, epoch=pin_epoch)   # the gather pin
        if not swapped:
            return True         # entry changed meanwhile: host copy
        #                         discarded, nothing to drop
        # The ENTRY's own page references are released now that its
        # payload lives on the host.
        mgr.unpin(payload.pages, epoch=pin_epoch)
        with self._stats_lock:
            self._host_bytes += spilled.nbytes
            self._host_entries += 1
            self._host_spills_total += 1
        self._enforce_spill_budget()
        return True

    def _enforce_spill_budget(self) -> None:
        """Drop the COLDEST host-tier entries until the spill bytes
        fit the budget (host-tier LRU — the radix recency order
        already is one)."""
        while True:
            with self._stats_lock:
                if self._host_bytes <= self.kv_host_spill_bytes:
                    return
            with self._prefix_lock:
                victim = None
                for t, p in self._prefix.entries():   # coldest first
                    if isinstance(p, _SpilledPrefix):
                        victim = (t, p)
                        break
                if victim is None:
                    return      # accounting drift guard
                self._prefix.remove(victim[0])
            self._free_displaced([victim])

    def _reclaim_prefix_pages(self, n_pages_needed: int) -> bool:
        """Free device pages until ``n_pages_needed`` are free (or no
        page-holding entry remains) — the engine's page-pressure
        hook: stored-but-idle prefixes must never starve admission of
        live traffic.  With the host tier enabled
        (``kv_host_spill_bytes > 0``) evicted entries SPILL their
        payloads to host RAM instead of dropping (tentpole b: the
        shareable-prefix working set multiplies by the host/HBM
        ratio); without it, this is the PR 7 drop-on-evict
        behavior."""
        mgr = self.engine.slots
        while mgr.free_page_count() < n_pages_needed:
            with self._prefix_lock:
                victim = None
                for t, p in self._prefix.entries():   # coldest first
                    if isinstance(p, _PagedPrefix):
                        victim = (t, p)
                        break
            if victim is None:
                return False
            toks, payload = victim
            if self.kv_host_spill_bytes > 0 \
                    and self._spill_entry(toks, payload):
                continue
            # Drop path (spill disabled, failed, or over budget):
            # remove the entry and release its page references —
            # guarded by payload identity, a concurrent overwrite's
            # fresh payload must not be dropped on the old one's
            # verdict.
            with self._prefix_lock:
                if self._prefix.set_payload(toks, payload,
                                            expect=payload):
                    self._prefix.remove(toks)
                else:
                    continue    # entry changed: re-evaluate
            self._free_displaced([(toks, payload)])
        return True

    def _prefix_store(self, toks: np.ndarray, logits, cache, *,
                      hot: bool = True) -> None:
        """Store a prompt's prefill for reuse.  Callers must NOT hold
        the device lock (the paged path scatters pages under it).

        Legacy entries keep the contiguous ``cache``.  Paged entries
        (single-row, paged engine) write the cache into POOL PAGES,
        sharing every page-aligned prefix page with the deepest
        stored ancestor (the radix parent) instead of re-storing it —
        a session extension of an N-page system prompt costs only its
        own suffix pages.

        ``hot=False`` marks a SPECULATIVE store (the per-request
        session store-back): it enters the index's COLD ring, so a
        stream of one-shot suffixes cycles itself out instead of
        flushing explicitly registered system prompts (scan
        resistance — see RadixPrefixIndex.store).  A store that
        could not survive insertion (capacity fully held by hot
        entries) is skipped BEFORE any device/page work."""
        toks = np.asarray(toks, np.int32)
        p_len = toks.shape[1]
        paged = self.kv_paged and toks.shape[0] == 1
        mgr = self.engine.slots if self.engine is not None else None
        shared = ()
        pin_epoch = None
        with self._prefix_lock:
            anc = self._prefix.longest_ancestor(toks)
            if anc is not None and anc[0].shape[1] >= p_len:
                return     # exact prompt already stored
            if not self._prefix.accepts(hot):
                return     # would be displaced in the same call
            if paged and anc is not None \
                    and isinstance(anc[1], _PagedPrefix):
                n_share = min(anc[0].shape[1] // mgr.page_tokens,
                              mgr.pages_needed(p_len))
                shared = tuple(anc[1].pages[:n_share])
                pin_epoch = mgr.pin(shared)
        if paged and pin_epoch is None:
            pin_epoch = mgr.epoch    # no ancestor pins: current gen
        if not paged:
            with self._prefix_lock:
                displaced = self._prefix.store(toks, (logits, cache),
                                               hot=hot)
            self._free_displaced(displaced)
            return
        n_pages = mgr.pages_needed(p_len)
        fresh, reserve_epoch = None, pin_epoch
        for _ in range(8):      # bounded: a reserve/consume race
            #                     must not spin this store forever
            fresh, reserve_epoch = mgr.reserve_with_epoch(
                n_pages - len(shared))
            if fresh is not None:
                break
            if not self._reclaim_prefix_pages(n_pages - len(shared)):
                break
        if fresh is None or reserve_epoch != pin_epoch:
            # Pool too tight to store (live traffic owns the pages)
            # — or rebuilt by crash recovery since the ancestor pins
            # were taken (mixed-generation ids must never enter the
            # index): skip quietly; the prefix cache is an
            # optimization, never back-pressure.  Epoch-guarded
            # unpins release only ids still current; dead-generation
            # ids drop by reference.
            mgr.unpin(shared, epoch=pin_epoch)
            if fresh:
                mgr.unpin(fresh, epoch=reserve_epoch)
            with self._stats_lock:
                self._prefix_store_skips += 1
                first = self._prefix_store_skips == 1
            if first:
                print("# serving: prefix store skipped — page pool "
                      "under live-traffic pressure (counted in "
                      "/info prefix_store_skips)", file=sys.stderr)
            return
        ids = list(shared) + fresh
        try:
            with self._lock:
                # Epoch re-check INSIDE the device lock: crash
                # recovery rebuilds the pool UNDER this lock, so a
                # dead-generation scatter (which would overwrite
                # pages the fresh pool already handed to residents)
                # cannot interleave — it either sees the bump here
                # and drops by reference, or completes before the
                # rebuild (whose recovery flush then wipes the
                # entry).
                if mgr.epoch != pin_epoch:
                    return
                mgr.scatter_cache(cache, ids,
                                  n_shared=len(shared))
        except BaseException:
            mgr.unpin(shared, epoch=pin_epoch)
            mgr.unpin(fresh, epoch=reserve_epoch)
            raise
        payload = _PagedPrefix(ids, p_len, logits)
        with self._prefix_lock:
            if mgr.epoch != pin_epoch:
                # Rebuilt after the scatter: the ids are dead and
                # the recovery flush owns the index — drop the
                # entry by reference.
                return
            displaced = self._prefix.store(toks, payload, hot=hot)
        self._free_displaced(displaced)

    def _store_stream_prefix(self, stream) -> None:
        """Engine ``on_prefilled`` hook for prefix-seeded streams:
        store the extended prompt's prefill back so an exact repeat
        hits at full length (session growth — same contract as the
        solo split path).  Runs on the engine thread, before the
        stream's cache is handed to the slot pool (arrays are
        immutable, so the stored entry and the slot copy never
        alias mutably)."""
        self._prefix_store_safe(np.asarray(stream.toks),
                                stream.logits, stream.cache,
                                hot=False)

    # -- fleet prefix cache (wire fetch / ingest / handoff) --------------

    @staticmethod
    def _prefix_key(toks: np.ndarray) -> str:
        """Stable cross-replica identity of one stored prompt: every
        replica (and the router's rebalance pass) derives the same
        key from the same tokens, so fleet inventory needs no shared
        namespace service."""
        import hashlib

        toks = np.ascontiguousarray(np.asarray(toks, np.int32))
        return hashlib.sha1(
            b"%d|%d|" % toks.shape + toks.tobytes()).hexdigest()

    def _note_fetch_failed(self, reason: str) -> None:
        with self._stats_lock:
            self._fetch_failed[reason] = \
                self._fetch_failed.get(reason, 0) + 1

    def _pack_entry_wire(self, ent_toks, payload) -> Optional[bytes]:
        """Serialize ONE radix entry for the wire.  Host-tier entries
        pack directly (immutable host buffers — no locks needed past
        the lookup that produced them).  Device-tier entries gather
        READ-ONLY: pin under the prefix lock, ``spill_pages`` under
        the device lock, unpin — the entry keeps its pages and its
        payload (unlike ``_spill_entry`` there is NO swap; serving a
        peer must not demote the holder's own hot copy).  Returns
        None when the entry vanished or the gather failed — callers
        treat that as a miss."""
        if isinstance(payload, _SpilledPrefix):
            return pack_spilled(ent_toks, payload.leaves,
                                payload.n_tokens, payload.logits)
        if not isinstance(payload, _PagedPrefix):
            return None     # legacy contiguous entries stay local
        import jax

        mgr = self.engine.slots
        with self._prefix_lock:
            # Identity-guarded presence check + pin under the prefix
            # lock — same discipline as _spill_entry's gather.
            if not self._prefix.set_payload(ent_toks, payload,
                                            expect=payload):
                return None
            pin_epoch = mgr.pin(payload.pages)
        try:
            with self._lock:
                if mgr.epoch != pin_epoch:
                    return None
                host = mgr.spill_pages(payload.pages,
                                       payload.n_tokens)
                logits_host = np.asarray(
                    jax.device_get(payload.logits))
        except Exception:
            return None
        finally:
            mgr.unpin(payload.pages, epoch=pin_epoch)
        return pack_spilled(ent_toks, host, payload.n_tokens,
                            logits_host)

    def prefix_wire_payload(self, req: Dict[str, Any]
                            ) -> Optional[bytes]:
        """POST /prefix/fetch: serve the longest stored entry that
        prefixes the peer's prompt, serialized for the wire.  Served
        even while DRAINING — the drain window is exactly when peers
        come asking.  None -> the handler's 404 (holder miss)."""
        if not self.kv_paged:
            raise ValueError(
                "prefix wire fetch requires a paged engine "
                "(kv_paged)")
        rows = _parse_prompt_rows(req, self.max_batch)
        toks = np.asarray(rows, np.int32)
        with self._prefix_lock:
            # lookup (not longest_ancestor): a fleet hit IS a hit —
            # it should refresh the entry's recency here too.
            hit = self._prefix.lookup(toks)
        if hit is None:
            return None
        return self._pack_entry_wire(hit[0], hit[1])

    def prefix_ingest(self, blob: bytes, *,
                      hot: bool = True) -> Dict[str, Any]:
        """POST /prefix/ingest: verify + store one wire payload as a
        HOST-TIER entry (a drain handoff's push, or a prefetch).  The
        payload is checksummed end to end — a mismatch raises the
        typed :class:`WirePayloadError` (400), and nothing partial is
        ever admitted.  Stored entries enter the spill byte budget
        exactly like locally-spilled ones."""
        if not self.kv_paged or self.kv_host_spill_bytes <= 0:
            with self._stats_lock:
                self._ingest_rejected_total += 1
            raise ValueError(
                "prefix ingest requires a paged engine with a host "
                "spill budget (--kv-host-spill-bytes)")
        if not self._prefix_enabled:
            with self._stats_lock:
                self._ingest_rejected_total += 1
            raise ValueError(
                "prefix cache is disabled on this server")
        try:
            toks, leaves, n_tokens, logits = unpack_spilled(blob)
        except WirePayloadError:
            with self._stats_lock:
                self._ingest_rejected_total += 1
            raise
        spilled = _SpilledPrefix(leaves, n_tokens, logits)
        if spilled.nbytes > self.kv_host_spill_bytes:
            with self._stats_lock:
                self._ingest_rejected_total += 1
            return {"stored": False, "reason": "over_budget",
                    "nbytes": spilled.nbytes,
                    "budget": self.kv_host_spill_bytes}
        with self._prefix_lock:
            anc = self._prefix.longest_ancestor(toks)
            if anc is not None and anc[0].shape[1] >= n_tokens:
                return {"stored": False, "reason": "already_stored"}
            if not self._prefix.accepts(hot):
                with self._stats_lock:
                    self._ingest_rejected_total += 1
                return {"stored": False, "reason": "at_capacity"}
            displaced = self._prefix.store(toks, spilled, hot=hot)
        self._free_displaced(displaced)
        with self._stats_lock:
            self._host_bytes += spilled.nbytes
            self._host_entries += 1
            self._ingest_total += 1
        self._enforce_spill_budget()
        return {"stored": True, "n_tokens": int(n_tokens),
                "nbytes": spilled.nbytes}

    def prefix_index(self) -> Dict[str, Any]:
        """GET /prefix/index: this replica's prefix inventory — the
        fleet eviction policy's input.  Each entry carries its stable
        cross-replica key, tier, recency ring, per-entry hit count,
        and (host tier) byte size, so the router can decide which
        spilled copies are redundant WITHOUT fetching any payload."""
        with self._prefix_lock:
            meta = self._prefix.entries_meta()
        entries = []
        for toks, payload, hits, hot in meta:
            if isinstance(payload, _SpilledPrefix):
                tier: Dict[str, Any] = {"tier": "host",
                                        "nbytes": payload.nbytes}
            elif isinstance(payload, _PagedPrefix):
                tier = {"tier": "device"}
            else:
                tier = {"tier": "legacy"}
            entries.append({"key": self._prefix_key(toks),
                            "rows": int(toks.shape[0]),
                            "tokens": int(toks.shape[1]),
                            "hits": int(hits),
                            "hot": bool(hot), **tier})
        with self._stats_lock:
            host_bytes = self._host_bytes
        return {"entries": entries,
                "host_bytes": host_bytes,
                "host_budget_bytes": self.kv_host_spill_bytes}

    def prefix_evict(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /prefix/evict: apply fleet eviction HINTS — drop the
        named HOST-TIER entries (the redundant cold copies the
        router's one-copy-somewhere policy identified).  Device-tier
        entries never drop on a hint: they are this replica's own
        working set, and fleet policy only governs the spill tier it
        can see through ``kv_host_*``.  Hints are advisory by
        construction — an unknown key is simply skipped."""
        keys = req.get("keys")
        if not isinstance(keys, list) \
                or not all(isinstance(k, str) for k in keys):
            raise ValueError("'keys' must be a list of entry keys "
                             "(GET /prefix/index)")
        want = set(keys)
        dropped = []
        with self._prefix_lock:
            for toks, payload in list(self._prefix.entries()):
                if not isinstance(payload, _SpilledPrefix):
                    continue
                if self._prefix_key(toks) in want:
                    self._prefix.remove(toks)
                    dropped.append((toks, payload))
        self._free_displaced(dropped)
        with self._stats_lock:
            self._evict_hints_total += len(dropped)
        return {"evicted": len(dropped),
                "requested": len(want)}

    def prefix_handoff(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /prefix/handoff: push this replica's prefix entries
        to a successor — the drain workflow's cache half (the router
        posts this between drain-complete and restart, so a rolling
        restart stops being a cache massacre).  Hottest entries
        first; device-tier entries ride too (gathered read-only —
        on a DRAINED replica the device lock is idle).  Serialization
        and every push happen OUTSIDE all locks, each over its own
        bounded connection; per-entry failures are counted and
        skipped, never raised — the restart must proceed whatever the
        successor says."""
        host = req.get("host")
        port = req.get("port")
        if not isinstance(host, str) or not host:
            raise ValueError("'host' must be a non-empty string")
        try:
            port = _int_param(port)
        except (TypeError, ValueError):
            raise ValueError("'port' must be an int")
        max_entries = req.get("max_entries")
        if max_entries is not None:
            max_entries = _int_param(max_entries)
            if max_entries < 1:
                raise ValueError("max_entries must be >= 1")
        include_device = req.get("include_device", True)
        if not isinstance(include_device, bool):
            raise ValueError("'include_device' must be a boolean")
        import http.client

        t0 = time.perf_counter()
        with self._prefix_lock:
            # entries() is coldest-first; the handoff budget should
            # go to the HOTTEST entries, so reverse.
            ents = [(t, p) for t, p in
                    reversed(self._prefix.entries())
                    if isinstance(p, _SpilledPrefix)
                    or (include_device
                        and isinstance(p, _PagedPrefix))]
        if max_entries is not None:
            ents = ents[:max_entries]
        sent = bytes_sent = failed = 0
        for ent_toks, payload in ents:
            blob = self._pack_entry_wire(ent_toks, payload)
            if blob is None:
                failed += 1
                continue
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.prefix_fetch_timeout_s)
                try:
                    conn.request(
                        "POST", "/prefix/ingest", body=blob,
                        headers={"Content-Type":
                                 "application/octet-stream"})
                    resp = conn.getresponse()
                    body = resp.read()
                finally:
                    conn.close()
                out = json.loads(body or b"{}") \
                    if resp.status == 200 else {}
                if out.get("stored"):
                    sent += 1
                    bytes_sent += len(blob)
                elif out.get("reason") == "already_stored":
                    sent += 1   # the successor already holds it —
                    #             the handoff's goal state
                else:
                    failed += 1
            except (OSError, ValueError,
                    http.client.HTTPException):
                failed += 1
        with self._stats_lock:
            self._handoff_entries_total += sent
            self._handoff_bytes_total += bytes_sent
            self._handoff_failed_total += failed
        t_end = time.perf_counter()
        # The handoff span rides the shared trace ring, so the
        # stitched fleet timeline can attribute the restart's cache
        # migration cost next to the drain/restart spans.
        self._push_solo_events(
            [("prefix_handoff", t0, t_end,
              {"to": f"{host}:{port}", "entries": sent,
               "bytes": bytes_sent, "failed": failed})])
        return {"sent": sent, "bytes": bytes_sent,
                "failed": failed, "considered": len(ents),
                "wall_s": round(t_end - t0, 4)}

    def _prefix_wire_fetch(self, toks: np.ndarray,
                           hint: Dict[str, Any]):
        """Affinity-miss wire fetch (the client half): ask the
        router-designated holder for the spilled payload, verify it,
        admit it through the host tier, and serve THIS request from
        it.  Returns ``(PrefixHit, fetch_span_events)`` or None; every
        failure lands in ``prefix_fetch_failed_total{reason=}`` and
        falls back to re-prefill — the fetch tier is an optimization,
        never a request dependency.  No locks are held across any
        socket work."""
        host, port = hint.get("host"), hint.get("port")
        if not host or not port:
            self._note_fetch_failed("bad_hint")
            return None
        # Router-measured link estimates ride the hint (EWMA over
        # completed fetches/handoffs + probe RTTs): when present the
        # cost gate runs on observed truth for this link instead of
        # the policy's static defaults.
        def _est(key):
            v = hint.get(key)
            try:
                return None if v is None else float(v)
            except (TypeError, ValueError):
                return None

        link_bw = _est("wire_bytes_per_s")
        link_rtt = _est("rtt_s")
        n_tokens = int(toks.shape[1])
        ok, why = self.fetch_policy.should_fetch(
            n_tokens, 0, wire_bytes_per_s=link_bw, rtt_s=link_rtt)
        if not ok:
            self._note_fetch_failed(why)
            return None
        import http.client

        with self._stats_lock:
            self._fetch_attempts_total += 1
        t0 = time.perf_counter()
        blob = None
        try:
            conn = http.client.HTTPConnection(
                str(host), int(port),
                timeout=self.prefix_fetch_timeout_s)
            try:
                conn.request(
                    "POST", "/prefix/fetch",
                    body=json.dumps(
                        {"prompt": toks.tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    self._note_fetch_failed(
                        "holder_miss" if resp.status == 404
                        else f"http_{resp.status}")
                    return None
                nbytes = int(resp.getheader("Content-Length") or 0)
                # The policy's second look, on the TRUE size, before
                # the body transfer: a veto here has paid one RTT and
                # headers, nothing more.
                ok, why = self.fetch_policy.should_fetch(
                    n_tokens, nbytes, wire_bytes_per_s=link_bw,
                    rtt_s=link_rtt)
                if not ok:
                    self._note_fetch_failed(why)
                    return None
                blob = resp.read()
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            self._note_fetch_failed("wire_error")
            return None
        try:
            ent_toks, leaves, pc, logits = unpack_spilled(blob)
        except WirePayloadError:
            self._note_fetch_failed("integrity")
            return None
        if ent_toks.shape[0] != toks.shape[0] or pc > n_tokens \
                or not np.array_equal(ent_toks, toks[:, :pc]):
            # Verified bytes but the WRONG prefix (a holder bug or a
            # stale hint): admitting it would poison the cache.
            self._note_fetch_failed("wrong_prefix")
            return None
        spilled = _SpilledPrefix(leaves, pc, logits)
        # Admit through the host tier (budget-gated) so later local
        # requests hit it without another wire trip...
        stored = False
        if spilled.nbytes <= self.kv_host_spill_bytes:
            with self._prefix_lock:
                anc = self._prefix.longest_ancestor(ent_toks)
                have = anc is not None \
                    and anc[0].shape[1] >= pc
                displaced = [] if have \
                    else self._prefix.store(ent_toks, spilled)
                stored = not have
            self._free_displaced(displaced)
            if stored:
                with self._stats_lock:
                    self._host_bytes += spilled.nbytes
                    self._host_entries += 1
                self._enforce_spill_budget()
        # ...then serve THIS request: the normal lookup path when the
        # entry landed (promotion and shared pages included), or a
        # direct re-materialization when it didn't — bitwise-
        # identical either way (rematerialize == materialize for the
        # same content).
        try:
            hit = self._prefix_lookup(toks) if stored else None
            if hit is None:
                hit = self._rematerialize_hit(ent_toks, spilled, pc)
        except Exception:
            self._note_fetch_failed("rematerialize")
            self._note_prefix_error("lookup")
            return None
        t_end = time.perf_counter()
        with self._stats_lock:
            self._fetch_hits_total += 1
            self._fetch_bytes_total += len(blob)
        events = [("prefix_wire_fetch", t0, t_end,
                   {"holder": str(hint.get("replica")
                                  or f"{host}:{port}"),
                    "bytes": len(blob), "tokens": int(pc)})]
        return hit, events

    def prefill_prompt(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /prefill: register a prompt (prefix) in the prefix
        cache — the system-prompt workflow.  Later /generate requests
        whose prompt starts with it skip its prefill entirely."""
        self._check_not_draining()
        if not self._prefix_enabled:
            raise ValueError(
                "prefix cache is disabled on this server "
                "(start with --prefix-cache N)"
                + (" — it disabled itself after a store error; see "
                   "/info prefix_store_errors"
                   if self._prefix_store_errors else ""))
        import jax

        rows = _parse_prompt_rows(req, self.max_batch)
        cfg = getattr(self.model, "cfg", None)
        max_pos = getattr(cfg, "max_position", None)
        if max_pos is not None and len(rows[0]) > max_pos \
                and not getattr(cfg, "kv_cache_ring", False):
            # same contract as /generate: doomed requests fail in the
            # cheap validation layer, not at jit-trace time inside
            # the device lock (an over-capacity prefill would clamp
            # the cache write index into garbage).
            raise ValueError(
                f"prompt ({len(rows[0])}) exceeds the model's "
                f"max_position ({max_pos})")
        chunk = req.get("prefill_chunk")
        try:
            chunk = None if chunk is None else _int_param(chunk)
        except (TypeError, ValueError):
            # normalized 400, same contract as /generate (a list or
            # string here must not surface as a 500 TypeError)
            raise ValueError("prefill_chunk must be an int")
        if chunk is not None and chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        toks = np.asarray(rows, np.int32)
        t0 = time.perf_counter()
        with self._lock, self._exact():
            logits, cache = self._split_fns(
                toks.shape[0], toks.shape[1], "pfill", chunk)(toks)
            jax.block_until_ready(logits)
        # Outside the device lock: the paged store re-acquires it for
        # its page scatter (locks never nest device -> prefix).
        self._prefix_store_safe(toks, logits, cache)
        with self._stats_lock:
            self.requests += 1
            self._lat_sum += time.perf_counter() - t0
            self._lat_count += 1
        return {"cached_rows": toks.shape[0],
                "cached_len": toks.shape[1],
                "entries": len(self._prefix)}

    def _generate_prefix_cached(self, toks: np.ndarray, p_len: int,
                                new: int, temp, top_k, top_p, eos,
                                chunk, seed, hit, deadline=None):
        """Solo decode through the split prefill/continue programs on
        a prefix-cache HIT, paying prefill only for the suffix (which
        is stored back, so sessions grow).  Exact: the split is the
        same program as fused generate (generate_continue's contract),
        and extension equals one-shot prefill (chunked-prefill
        contract).  Sampled hits run the position-keyed continue —
        token indices restart at 0 for the new tokens, so a warm hit
        draws the same stream a cold request would."""
        import jax
        import jax.random as jrandom

        from ..models import generate as G

        b = toks.shape[0]
        store_back = None
        try:
            with self._lock, self._exact():
                if deadline is not None \
                        and time.perf_counter() > deadline:
                    # Same contract as the other solo branches: the
                    # split decode is fused dispatches that can't stop
                    # mid-flight, so the deadline is honored up to the
                    # device-lock acquisition.
                    raise DeadlineExceeded(
                        "deadline exceeded waiting for the device "
                        "(prefix-cache solo path)")
                pc, logits, cache = hit.p_cached, hit.logits, hit.cache
                if pc < p_len:  # extend with the suffix, store back
                    suffix = toks[:, pc:]
                    logits, cache = self._split_fns(
                        b, suffix.shape[1], "extend", chunk)(
                            cache, suffix, pc)
                    jax.block_until_ready(logits)
                    store_back = (logits, cache)
                if G.positional_eligible(self.model, temp):
                    keys = np.asarray(G.sample_stream_keys(seed, b))
                    fn = self._split_fns(b, None, "cont_pos", chunk,
                                         new=new, eos=eos)
                    out_new = np.asarray(jax.device_get(fn(
                        cache, logits, p_len, keys, np.float32(temp),
                        np.int32(top_k or 0),
                        np.float32(top_p or 0.0))))
                else:
                    out_new = np.asarray(jax.device_get(
                        self._split_fns(
                            b, None, "cont", chunk, new=new, temp=temp,
                            top_k=top_k, top_p=top_p, eos=eos)(
                            cache, logits, p_len,
                            jrandom.PRNGKey(seed))))
        finally:
            # The solo path never maps shared pages into a slot — the
            # materialized cache is an independent copy.
            self._unpin_prefix(hit.pins)
        if store_back is not None:
            # Outside the device lock: the paged store re-acquires
            # it.  Cold insertion: one speculative store-back per
            # request must never flush a registered system prompt.
            self._prefix_store_safe(toks, *store_back, hot=False)
        with self._stats_lock:
            self.requests += 1
            self.prefix_hits += 1
            self.prefix_hit_tokens += hit.p_cached
        return np.concatenate([toks, out_new], axis=1)

    # -- request handling -----------------------------------------------

    def generate(self, req: Dict[str, Any],
                 cancel_check=None,
                 rid: Optional[str] = None) -> Dict[str, Any]:
        import jax

        # Correlation ID: the HTTP handler passes the inbound (or
        # generated) X-Request-Id; library callers get one here so
        # every request carries an ID into its trace spans and its
        # history record whichever surface submitted it.
        if rid is None:
            rid = new_request_id()
        # Draining sheds BEFORE validation work: the router already
        # saw readiness drop; anything still arriving gets the
        # structured 503 immediately.
        self._check_not_draining()
        if self.role == "prefill":
            # A role-split fleet never routes /generate here (the
            # router's capability filter excludes prefill replicas);
            # a direct caller gets the typed 400 rather than a decode
            # stream quietly competing with the prefill tier.
            raise ValueError(
                "this replica runs role='prefill': it serves "
                "/prefill and /prefix/* only — send /generate to a "
                "decode-capable replica (role 'decode' or 'both')")
        rows = _parse_prompt_rows(req, self.max_batch)
        lens = [len(r) for r in rows]
        _int = _int_param

        def _float(v):
            # float(True) == 1.0: {"temperature": true} must not
            # silently switch greedy to temp-1.0 sampling.
            if isinstance(v, bool):
                raise ValueError("expected a number, got a boolean")
            return float(v)

        try:
            new = _int(req.get("max_new_tokens", 32))
            temp = _float(req.get("temperature", 0.0))
            top_k = req.get("top_k")
            top_k = None if top_k is None else _int(top_k)
            top_p = req.get("top_p")
            top_p = None if top_p is None else _float(top_p)
            eos = req.get("eos_id")
            eos = None if eos is None else _int(eos)
            beams = _int(req.get("num_beams", 1))
            seed = _int(req.get("seed", 0))
        except (TypeError, ValueError):
            raise ValueError(
                "sampling params must be scalars (temperature/top_p "
                "float, max_new_tokens/top_k/eos_id/num_beams/seed "
                "int, not booleans)")
        if new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # Uniform sampling-param validation: ONE message for every
        # path (engine, coalesce, solo, speculative, prefix hits),
        # raised here so doomed requests fail in this cheap layer —
        # never at jit-trace time inside the device lock, and never
        # differently depending on which batching mode fields them.
        from ..models.generate import (SPEC_BEAM_MSG,
                                       _check_spec_k,
                                       _check_temperature,
                                       _check_top_k, _check_top_p)

        _check_top_k(top_k, getattr(getattr(self.model, "cfg", None),
                                    "vocab_size", None))
        _check_top_p(top_p)
        _check_temperature(temp)
        if beams > 1 and (temp != 0.0 or top_k is not None
                          or top_p is not None):
            # Mirror the CLI: beam search is deterministic — dropping
            # sampling params silently would let a client believe it
            # sampled.
            raise ValueError(
                "beam search is deterministic; temperature/top_k/"
                "top_p cannot be combined with num_beams > 1")
        speculative = req.get("speculative", False)
        if not isinstance(speculative, bool):
            # bool("false") is True — a stringified flag must not
            # silently flip the decode mode.
            raise ValueError("'speculative' must be a JSON boolean")
        want_timings = req.get("timings", False)
        if not isinstance(want_timings, bool):
            raise ValueError("'timings' must be a JSON boolean")
        # Lifecycle params: the priority class (server default when
        # absent) and an optional relative deadline in ms — expiry
        # evicts the request at the next step boundary (504).
        priority = req.get("priority", self.default_priority)
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {list(PRIORITIES)}; got "
                f"{priority!r}")
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = _int(deadline_ms)
            except (TypeError, ValueError):
                raise ValueError("deadline_ms must be an int")
            if deadline_ms < 1:
                raise ValueError("deadline_ms must be >= 1")
        deadline_s = None if deadline_ms is None \
            else deadline_ms / 1e3
        if speculative:
            if self.draft_model is None:
                raise ValueError(
                    "server has no draft model (start with "
                    "--draft-model to enable speculative decoding)")
            if beams > 1:
                raise ValueError(SPEC_BEAM_MSG)
            if temp == 0.0 and (top_k is not None
                                or top_p is not None):
                # dropping the flags silently would let a client
                # believe it sampled (same contract as num_beams)
                raise ValueError(
                    "speculative top_k/top_p need temperature > 0 "
                    "(temperature=0 is greedy and would ignore them)")
            try:
                spec_k = _int(req.get("spec_k", self.spec_k_default))
            except (TypeError, ValueError):
                raise ValueError("spec_k must be an int")
            _check_spec_k(spec_k)
        chunk = req.get("prefill_chunk")
        try:
            chunk = None if chunk is None else _int(chunk)
        except (TypeError, ValueError):
            raise ValueError("prefill_chunk must be an int")
        if chunk is not None and chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        p_len0 = lens[0]
        if chunk is not None and chunk >= p_len0:
            # a chunk covering the whole prompt IS the single-forward
            # program — normalize so identical programs share one
            # compile-cache slot
            chunk = None

        p_len = lens[0]
        # CROSS-REPLICA RESUME (docs/DESIGN.md): ``resume_tokens: N``
        # declares the trailing N prompt tokens a prior attempt's
        # committed output (a router failover replaying ``prompt ++
        # tokens_received_so_far``).  The engine re-enters the
        # request through the preempt-resume machinery, so sampled
        # draws continue at position key N — token-identical to the
        # uninterrupted run, per seed, on any replica.
        resume_tokens = req.get("resume_tokens", 0)
        try:
            resume_tokens = _int(resume_tokens)
        except (TypeError, ValueError):
            raise ValueError("resume_tokens must be an int")
        if resume_tokens < 0:
            raise ValueError("resume_tokens must be >= 0")
        if resume_tokens:
            if beams > 1:
                raise ValueError(
                    "resume_tokens cannot combine with beam search "
                    "(beam requests replay whole)")
            if self.engine is None:
                raise ValueError(
                    "resume_tokens requires the continuous-batching "
                    f"engine (batching={self.batching!r})")
            if len(rows) != 1:
                raise ValueError(
                    "resume_tokens takes a single-row request")
        # The EFFECTIVE prompt length: what the slot actually holds —
        # a resume replay's original prompt, not the concatenation.
        eff_p_len = p_len - resume_tokens
        if resume_tokens and eff_p_len < 1:
            raise ValueError(
                f"resume_tokens ({resume_tokens}) must leave at "
                f"least one original prompt token (prompt length "
                f"{p_len})")
        # Capacity checks for EVERY model a request will touch, so
        # doomed requests fail in this cheap validation layer instead
        # of inside the locked device section at jit-trace time.
        # Speculative rounds touch k-1 positions past the last
        # committed token (generate_speculative's guards).
        slack = (spec_k - 1) if speculative else 0
        models = [("model", self.model)]
        if speculative:
            models.append(("draft model", self.draft_model))
        for label, m in models:
            cfg = getattr(m, "cfg", None)
            max_pos = getattr(cfg, "max_position", None)
            if getattr(cfg, "kv_cache_ring", False):
                ring_slack = getattr(cfg, "kv_cache_ring_slack", 0)
                if speculative and ring_slack < spec_k - 1:
                    raise ValueError(
                        f"{label} needs kv_cache_ring_slack >= "
                        f"{spec_k - 1} for spec_k={spec_k} "
                        f"(got {ring_slack})")
                continue  # ring caches are position-keyed, unbounded
            if max_pos is not None \
                    and eff_p_len + new + slack > max_pos:
                raise ValueError(
                    f"prompt ({eff_p_len}) + max_new_tokens ({new})"
                    + (f" + spec_k-1 ({slack})" if slack else "")
                    + f" exceeds the {label}'s max_position "
                    f"({max_pos})")
        toks = np.asarray(rows, np.int32)

        t0 = time.perf_counter()
        # Prefix-cache hit (registered via /prefill): engine-eligible
        # B=1 hits (greedy OR sampled) ride the engine seeded with the
        # stored prefill; multi-row and engine-less hits decode from
        # it on the solo split path — beam tiles and speculative rolls
        # back the cache, so they stay cold.
        prefix_hit = None
        fetch_events = None
        if self._prefix_enabled and beams == 1 and not speculative \
                and not resume_tokens:
            # Resume replays skip the prefix store: the replayed
            # tokens ARE the state, and a store hit would re-seed a
            # stream the resume machinery is about to re-prefill.
            prefix_hit = self._prefix_lookup_safe(toks)
            if prefix_hit is None and self.prefix_fetch \
                    and isinstance(req.get("prefix_hint"), dict):
                # Local miss + a router hint naming the holder: try
                # the fleet tier.  Any failure inside lands in
                # prefix_fetch_failed_total{reason=} and leaves
                # prefix_hit None — this request just re-prefills.
                fetched = self._prefix_wire_fetch(
                    toks, req["prefix_hint"])
                if fetched is not None:
                    prefix_hit, fetch_events = fetched
        # Where this request's prefill came from — reported in the
        # response (the router learns holders from it) and in the
        # trace timeline's per-request "prefix source" column.
        if prefix_hit is None:
            prefix_source = "re_prefill"
        elif fetch_events is not None:
            prefix_source = "wire_fetch"
        elif prefix_hit.source == "host":
            prefix_source = "local_spilled"
        else:
            prefix_source = "local_hot"
        # Engine eligibility: any non-beam request on a decoder-only
        # model — greedy, sampled, AND speculative (the engine owns
        # the draft model whenever the server does).  temperature==0
        # streams are greedy (top_k/top_p inert, exactly like solo
        # _sample); temperature>0 streams sample per-slot under the
        # position-keyed RNG contract; speculative streams draft/
        # verify per round under the same contract — co-tenancy never
        # changes tokens on any lane.
        engine_ok = self.engine is not None and beams == 1
        if speculative and self.engine is None:
            # The satellite fix: engine-less modes used to drop
            # speculative requests to solo SILENTLY.
            self._note_fallback(
                "speculative",
                f"batching={self.batching!r} has no decode engine; "
                f"speculative requests hold the device lock for a "
                f"whole solo decode")
        if engine_ok and self.draft_model is not None:
            # A spec-capable pool verifies a cap+1-wide chunk per
            # round for EVERY resident, so every engine request —
            # co-tenants included — must leave cap-1 slack at the
            # cache end; and a spec_k above the cap would widen the
            # pool program past what co-tenants were admitted for.
            cap = self.spec_k_default
            cfg = getattr(self.model, "cfg", None)
            max_pos = getattr(cfg, "max_position", None)
            ring = getattr(cfg, "kv_cache_ring", False)
            if speculative and spec_k > cap:
                engine_ok = False
                self._note_fallback(
                    "speculative (spec_k over cap)",
                    f"request spec_k {spec_k} exceeds the engine cap "
                    f"{cap} (--spec-k); decoding solo")
            elif not ring and max_pos is not None \
                    and eff_p_len + new + cap - 1 > max_pos:
                engine_ok = False
                self._note_fallback(
                    "near-capacity",
                    f"prompt + max_new_tokens within {cap - 1} "
                    f"tokens of max_position ({max_pos}) cannot "
                    f"co-tenant a speculative pool (verify chunks "
                    f"are {cap + 1} wide); decoding solo")
        if resume_tokens and not engine_ok:
            # A request that fell off the engine (spec_k over cap,
            # near-capacity spec pool) replays WHOLE: solo paths have
            # no resume machinery, and silently restarting the RNG at
            # index 0 would break the token-identity contract.
            raise ValueError(
                "resume_tokens requires the engine path for this "
                "request (it fell back solo); replay the request "
                "without resume_tokens instead")
        sampling = None
        if speculative:
            sampling = SamplingSpec(seed, temp, top_k, top_p,
                                    spec_k=spec_k)
        elif temp != 0.0:
            sampling = SamplingSpec(seed, temp, top_k, top_p)
        # The coalescer merges plain greedy requests ONLY — beam and
        # speculative greedy requests must keep their solo programs
        # (a coalesced argmax batch would silently answer a beam
        # request with greedy tokens).
        greedy = temp == 0.0 and beams == 1 and not speculative
        breakdown = None
        # Telemetry anchors: ``group`` (engine paths) carries the
        # stream span lists + the TTFT anchor; solo/coalesce paths
        # collect their coarser spans in ``solo_events``.
        group = None
        solo_events = None
        if prefix_hit is not None and engine_ok \
                and toks.shape[0] == 1:
            # Prefix hit on the engine path: seed a stream with the
            # stored prefill so the request pays only its suffix (or
            # no prefill at all on a full-length hit) and DECODES IN A
            # SLOT like cold traffic — same decode program, and no
            # whole-decode device-lock hold stalling resident streams.
            # Paged engines additionally map the stored prefix's FULL
            # pages read-only into the admitted slot's table
            # (``shared_pages`` — copy-on-write sharing, so N hits of
            # one system prompt hold ONE copy of its KV); the engine
            # owns those pins once submit returns.
            pc, lg, cache = (prefix_hit.p_cached, prefix_hit.logits,
                             prefix_hit.cache)
            try:
                group = self.engine.submit(
                    toks, new, eos, chunk, sampling=sampling,
                    prefix=(pc, lg, cache),
                    on_prefilled=self._store_stream_prefix,
                    record_timings=want_timings,
                    priority=priority, deadline_s=deadline_s,
                    shared_pages=prefix_hit.pins or None,
                    rid=rid,
                    # Hit provenance for the history record: how
                    # many prompt tokens the stored prefill covered
                    # and how many pool pages the slot mapped
                    # read-only instead of refilling.
                    prefix_info={"cached_tokens": pc,
                                 "shared_pages":
                                     len(prefix_hit.pins or ()),
                                 "source": prefix_source},
                    pre_events=fetch_events)
            except BaseException:
                self._unpin_prefix(prefix_hit.pins)
                raise
            if fetch_events:
                # The wire-fetch span also rides the shared trace
                # ring so the stitched fleet timeline shows the
                # holder round-trip next to this request's spans.
                self._push_solo_events(list(fetch_events), rid=rid)
            self._wait_group(group, cancel_check)
            out = group.result()
            breakdown = group.breakdown()
            with self._stats_lock:
                self.requests += 1
                self.prefix_hits += 1
                self.prefix_hit_tokens += pc
        elif prefix_hit is not None:
            out = self._generate_prefix_cached(
                toks, p_len, new, temp, top_k, top_p, eos, chunk,
                seed, prefix_hit,
                deadline=t0 + deadline_s
                if deadline_s is not None else None)
            if fetch_events:
                self._push_solo_events(list(fetch_events), rid=rid)
            solo_events = self._emit_solo(t0, "prefix_solo",
                                          len(rows), rid=rid)
            if fetch_events:
                solo_events = list(fetch_events) + solo_events
        elif engine_ok:
            # CONTINUOUS BATCHING: per-row decode streams through the
            # slot pool.  Greedy streams ignore ``seed`` (greedy
            # decoding never consults the PRNG — identical output in
            # a slot or solo); sampled streams carry (seed,
            # temperature, top_k, top_p) into their slot and draw
            # token i with fold_in(fold_in(PRNGKey(seed), row), i).
            # May raise QueueFullError -> 429.
            group = self.engine.submit(toks, new, eos, chunk,
                                       sampling=sampling,
                                       record_timings=want_timings,
                                       priority=priority,
                                       deadline_s=deadline_s,
                                       rid=rid,
                                       resume_tokens=resume_tokens)
            self._wait_group(group, cancel_check)
            out = group.result()
            breakdown = group.breakdown()
            with self._stats_lock:
                self.requests += 1
        elif greedy and self._coalescer is not None:
            # Deadline is honored INSIDE the coalescer, at its one
            # boundary (post-lock, pre-dispatch) — same contract as
            # the solo branch's check under the device lock.
            out = self._coalescer.generate(
                toks, p_len, new, eos, chunk,
                deadline=t0 + deadline_s
                if deadline_s is not None else None)
            # The coalescer's queue wait is its device-lock wait,
            # folded inside generate() — one opaque span, honest
            # about the granularity this path offers.
            solo_events = self._emit_solo(t0, "coalesce_decode",
                                          len(rows), rid=rid)
        else:
            from ..models import generate as G

            positional = (not speculative and beams == 1
                          and G.positional_eligible(self.model, temp))
            # Sampled speculative solo runs the POSITION-KEYED seed
            # mode (generate_speculative keys=...), the same schedule
            # the engine's spec slots run — so a request returns the
            # same tokens whichever batching mode fields it.  Greedy
            # speculative has no randomness (its solo program already
            # equals the engine's greedy-spec commits).
            spec_pos = (speculative and temp != 0.0
                        and not hasattr(self.model, "encode"))
            if speculative:
                # last slot carries the draft length (see _fn)
                key = ("spec_pos" if spec_pos else "spec",
                       len(rows), p_len, new, temp, top_k,
                       top_p, eos, spec_k, chunk)
            elif beams > 1:
                key = ("beam", len(rows), p_len, new, temp, top_k,
                       top_p, eos, beams, chunk)
            elif positional:
                # decoder-only sampled solo (batching off/coalesce):
                # the position-keyed reference program — shaping
                # params fed at RUN TIME, so one compiled program per
                # shape serves every sampled combo, and the tokens
                # equal the engine's for the same request + seed
                key = ("sample_pos", len(rows), p_len, new, None,
                       None, None, eos, 1, chunk)
            else:
                key = ("sample", len(rows), p_len, new, temp, top_k,
                       top_p, eos, beams, chunk)
            t_lock = time.perf_counter()
            # one chip (or one mesh): serialize device work
            with self._lock, self._exact():
                import jax.random as jrandom

                queue_s = time.perf_counter() - t_lock
                if deadline_s is not None \
                        and time.perf_counter() - t0 > deadline_s:
                    # Solo programs are one fused dispatch — the
                    # deadline can only be honored BEFORE it (a
                    # request that expired waiting on the device
                    # lock sheds without burning device time).
                    raise DeadlineExceeded(
                        f"deadline exceeded after {queue_s:.3f}s "
                        f"waiting for the device (solo path)")
                fn = self._fn(key)
                if positional:
                    keys = np.asarray(
                        G.sample_stream_keys(seed, len(rows)))
                    out = np.asarray(jax.device_get(fn(
                        toks, keys, np.float32(temp),
                        np.int32(top_k or 0),
                        np.float32(top_p or 0.0))))
                elif spec_pos:
                    keys = np.asarray(
                        G.sample_stream_keys(seed, len(rows)))
                    out = np.asarray(jax.device_get(fn(toks, keys)))
                else:
                    out = np.asarray(jax.device_get(
                        fn(toks, jrandom.PRNGKey(seed))))
            with self._stats_lock:
                self.requests += 1
            breakdown = (queue_s, 0.0,
                         time.perf_counter() - t_lock - queue_s)
            t_end = time.perf_counter()
            solo_events = [
                ("queue", t_lock, t_lock + queue_s,
                 {"kind": key[0]}),
                ("solo_decode", t_lock + queue_s, t_end,
                 {"kind": key[0], "rows": len(rows)}),
                ("complete", t_end, t_end, {})]
            self._push_solo_events(solo_events, rid=rid)
        dt = time.perf_counter() - t0
        if breakdown is not None:
            self._note_breakdown(*breakdown)
            # Latency histograms (telemetry.py): queue-wait, prefill
            # and decode-per-token come from the phase breakdown;
            # solo requests report prefill 0 (fused into the decode
            # program — documented in docs/SERVING.md).  Per-token
            # divides by tokens actually DECODED: engine streams
            # evict at eos (len(out)), solo programs step the whole
            # budget (eos-frozen rows keep stepping).
            if group is not None:
                tokens_done = sum(len(s.out) for s in group.streams)
            else:
                tokens_done = len(rows) * new
            # Histogram KEY (telemetry.HIST_SPECS), not a ledger
            # phase reference.  # ptpu: ignore[PHASE-ENUM]
            self.telemetry.observe("queue_wait", breakdown[0],
                                   exemplar=rid)
            self.telemetry.observe("prefill", breakdown[1],
                                   exemplar=rid)
            self.telemetry.observe(
                "decode_per_token",
                breakdown[2] / max(1, tokens_done), exemplar=rid)
        # TTFT: the engine samples token 0 at admission; solo paths
        # deliver all tokens at once, so their client-visible TTFT is
        # the full latency.
        ttft = dt
        if group is not None and group.t_first_admit is not None:
            ttft = group.t_first_admit - group.t_submit
        self.telemetry.observe("ttft", ttft, exemplar=rid)
        self.telemetry.observe("total", dt, exemplar=rid)
        # Phase ledger (serving/forensics.py): the SAME function the
        # engine's history record runs, over the SAME events — the
        # timings block and GET /requests/<id> carry identical
        # ledgers by construction.  Solo paths (no engine terminal
        # hook) feed the forensics core from here.
        ledger = None
        if self.forensics is not None or want_timings:
            if group is not None:
                all_events: List = []
                for s in group.streams:
                    if s.events:
                        all_events.extend(s.events)
                t_done = group.t_done \
                    if group.t_done is not None else t0 + dt
                ledger = compute_ledger(all_events, group.t_submit,
                                        t_done)
            elif solo_events is not None:
                ledger = compute_ledger(solo_events, t0, t0 + dt,
                                        solo=True)
                if self.forensics is not None:
                    self.forensics.note(ledger, rid)
        timings = None
        if want_timings:
            timings = {"ttft_ms": round(1e3 * ttft, 3)}
            if group is not None:
                timings["streams"] = [
                    {"row": s.row,
                     "spans": _span_dicts(s.events or [],
                                          group.t_submit)}
                    for s in group.streams]
            elif solo_events is not None:
                timings["spans"] = _span_dicts(solo_events, t0)
            if ledger is not None:
                timings["phases"] = ledger
        with self._stats_lock:
            self._lat_sum += dt
            self._lat_count += 1
            self._tokens_out += len(rows) * new
        # Engine-path provenance for the response AND the access log
        # (log_access copies these fields): which slot(s) served the
        # request, and whether it was preempted/resumed along the way
        # — a resumed request must be distinguishable from a
        # straight-through one in the log.
        eng_fields: Dict[str, Any] = {}
        if group is not None:
            slots_used = [s.last_slot for s in group.streams
                          if s.last_slot is not None]
            if slots_used:
                eng_fields["slot"] = slots_used[0] \
                    if len(slots_used) == 1 else slots_used
            pre = sum(s.preempts for s in group.streams)
            res = sum(s.resumes for s in group.streams)
            if pre or res:
                eng_fields["preempts"] = pre
                eng_fields["resumes"] = res
        return {
            "model": self.model_name,
            "request_id": rid,
            "new_tokens": out[:, p_len:].tolist(),
            "tokens": out.tolist(),
            "wall_s": round(dt, 4),
            "tok_per_sec": round(len(rows) * new / dt, 1),
            **eng_fields,
            **({"queue_ms": round(1e3 * breakdown[0], 3),
                "prefill_ms": round(1e3 * breakdown[1], 3),
                "decode_ms": round(1e3 * breakdown[2], 3)}
               if breakdown is not None else {}),
            **({"prefix_hit_len": prefix_hit.p_cached}
               if prefix_hit is not None else {}),
            # Always present when the prefix store is armed: the
            # router's affinity learner and trace_report's "prefix
            # source" column both read it.
            **({"prefix_source": prefix_source}
               if self._prefix_enabled else {}),
            # Wire-fetch measurement for the router's link
            # calibration (EWMA wire_bytes_per_s): the observed
            # payload size + wall time of the fetch that served this
            # request, straight from its span.
            **({"prefix_fetch_bytes": fetch_events[0][3]["bytes"],
                "prefix_fetch_s": round(
                    fetch_events[0][2] - fetch_events[0][1], 6)}
               if fetch_events else {}),
            **({"timings": timings} if timings is not None else {}),
        }

    # -- telemetry helpers ----------------------------------------------

    def _push_solo_events(self, events,
                          rid: Optional[str] = None) -> None:
        """Emit a solo/coalesce request's span tuples onto the shared
        trace ring (one fresh track per request).  ``rid`` is stamped
        into every span's args — solo paths must be as findable by
        request ID as engine paths (the correlation contract in
        docs/SERVING.md)."""
        tid = self.telemetry.new_tid()
        for name, a, b, args in events:
            if rid is not None:
                args.setdefault("rid", rid)
            if a == b:
                self.telemetry.instant(tid, name, a, **args)
            else:
                self.telemetry.span(tid, name, a, b, **args)

    def _emit_solo(self, t0: float, name: str, rows: int,
                   rid: Optional[str] = None):
        """One opaque span for paths whose internal phases are fused
        (coalescer, prefix-cache split decode): arrival -> now."""
        t_end = time.perf_counter()
        events = [(name, t0, t_end, {"rows": rows}),
                  ("complete", t_end, t_end, {})]
        self._push_solo_events(events, rid=rid)
        return events

    def _spill_stats(self) -> Dict[str, Any]:
        """The host-spill tier's counters — ONE dict rendered by
        BOTH /metrics and /info (the no-drift pin, like every prior
        PR's counter families)."""
        with self._stats_lock:
            return {
                "kv_host_spill_bytes": self._host_bytes,
                "kv_host_spill_bytes_budget":
                    self.kv_host_spill_bytes,
                "kv_host_entries": self._host_entries,
                "kv_host_spills_total": self._host_spills_total,
                "kv_host_dropped_total": self._host_dropped_total,
                "kv_rematerialize_hits_total": self._remat_hits_total,
                "kv_rematerialize_bytes_total":
                    self._remat_bytes_total,
                "kv_promotions_total": self._promotions_total,
                "prefix_fetch_total": self._fetch_attempts_total,
                "prefix_fetch_hits_total": self._fetch_hits_total,
                "prefix_fetch_bytes_total": self._fetch_bytes_total,
                "prefix_fetch_failed": dict(self._fetch_failed),
                "prefix_ingest_total": self._ingest_total,
                "prefix_ingest_rejected_total":
                    self._ingest_rejected_total,
                "prefix_handoff_entries_total":
                    self._handoff_entries_total,
                "prefix_handoff_bytes_total":
                    self._handoff_bytes_total,
                "prefix_handoff_failed_total":
                    self._handoff_failed_total,
                "prefix_evict_hints_total": self._evict_hints_total,
            }

    def info(self) -> Dict[str, Any]:
        import jax

        cfg = getattr(self.model, "cfg", None)
        summary = {}
        if cfg is not None:
            for f in ("vocab_size", "hidden_size", "d_model",
                      "num_layers", "num_heads", "max_position",
                      "kv_cache_int8"):
                v = getattr(cfg, f, None)
                if v is not None:
                    summary[f] = v
        engine = self.engine.stats() if self.engine is not None else {}
        # Routing report: where each request class decodes on THIS
        # server config, plus the dynamic solo-fallback table (kinds
        # that dropped to solo at request time, with the logged
        # reason and a count).
        if self.engine is not None:
            spec_route = ("engine" if self.draft_model is not None
                          else "unavailable (no draft model)")
            routing = {"greedy": "engine", "sampled": "engine",
                       "speculative": spec_route, "beam": "solo"}
        else:
            routing = {
                "greedy": "coalesce" if self.batching == "coalesce"
                else "solo",
                "sampled": "solo",
                "speculative": "solo" if self.draft_model is not None
                else "unavailable (no draft model)",
                "beam": "solo"}
        with self._stats_lock:
            fallbacks = {k: dict(v)
                         for k, v in self.solo_fallbacks.items()}
        # Recompile sentinel in the routing report: a healthy routing
        # table with a climbing miss count under steady traffic means
        # some request property is leaking into program keys.
        compile_cache = self.recompile.snapshot()
        return {"model": self.model_name, "config": summary,
                "backend": jax.default_backend(),
                "max_batch": self.max_batch,
                "batching": self.batching,
                "role": self.role,
                "spec_k_default": self.spec_k_default,
                "default_priority": self.default_priority,
                # Engine-less modes still drain (solo/coalesce paths
                # shed at validation); the engine passthrough below
                # overwrites with its own latch, which drain() sets
                # in the same call.
                "draining": self.draining,
                "drain_rejected_total": self.drain_rejected,
                "routing": routing,
                "solo_fallbacks": fallbacks,
                "compile_cache_misses":
                    compile_cache["compile_cache_misses"],
                "compile_cache": compile_cache,
                **({"sanitizer": self.sanitizer.stats()}
                   if self.sanitizer is not None else {}),
                # Request-scoped debuggability: the history ring's
                # occupancy (GET /requests) and the stall watchdog's
                # arming/knobs + fire count when enabled.
                "debug": {
                    **self.history.stats(),
                    **({"watchdog": self.watchdog.status()}
                       if self.watchdog is not None else {})},
                # Flight-recorder attribution (serving/profiling.py):
                # summarized from the SAME published record /metrics
                # and GET /profile/report render.
                **({"profiling": self.recorder.info_block()}
                   if self.recorder is not None else {}),
                "compiled_shapes": len(self._fns),
                "requests": self.requests,
                "coalesced_batches": self.coalesced_batches,
                "coalesced_requests": self.coalesced_requests,
                "prefix_entries": len(self._prefix),
                "prefix_hits": self.prefix_hits,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prefix_store_skips": self._prefix_store_skips,
                # Degradation ladder: error count + the live enabled
                # flag (False after a store error OR prefix_cache=0).
                "prefix_store_errors": self._prefix_store_errors,
                "prefix_enabled": self._prefix_enabled,
                # Fault tolerance: the supervisor's full status block
                # and the armed fault plan's counters (the engine
                # passthrough below carries the flat counter keys —
                # same engine.stats() dict /metrics renders).
                **({"supervisor": self.supervisor.status()}
                   if self.supervisor is not None else {}),
                **({"fault_plan": self.faults.stats()}
                   if self.faults is not None else {}),
                "kv_paged": self.kv_paged,
                "kv_lazy": self.kv_lazy,
                # Host-spill tier (tentpole b): bytes/entries/hit
                # counters from the same _spill_stats() dict /metrics
                # renders.
                **(self._spill_stats() if self.kv_paged else {}),
                # Fleet prefix cache: whether the wire-fetch client
                # is armed, and the policy curve it gates on.
                "prefix_fetch": self.prefix_fetch,
                **({"prefix_fetch_policy":
                    self.fetch_policy.describe()}
                   if self.prefix_fetch else {}),
                **{k: engine[k] for k in
                   ("slots", "slots_active", "slot_occupancy",
                    "queue_len", "queue_depth", "admitted_total",
                    "admitted_greedy_total", "admitted_sampled_total",
                    "admitted_spec_total",
                    "evicted_total", "decode_steps_total",
                    "prefill_chunks_total", "completed_total",
                    "completed_greedy_total",
                    "completed_sampled_total",
                    "completed_spec_total",
                    "rejected_total",
                    "cancelled_total", "expired_total", "shed_total",
                    "shed_interactive_total", "shed_batch_total",
                    "preempted_total", "resumed_total",
                    "admitted_interactive_total",
                    "admitted_batch_total",
                    "queue_len_interactive", "queue_len_batch",
                    "draining",
                    "engine_down", "step_retries_total",
                    "requests_requeued_total", "poisoned_total",
                    "telemetry_errors_total",
                    "engine_crashes_total", "engine_restarts_total",
                    "breaker_state", "faults_injected_total",
                    "faults_injected",
                    "shed_kv_pages_total",
                    "kv_pages", "kv_page_tokens", "kv_pages_free",
                    "kv_pages_resident", "kv_pages_shared",
                    "kv_pages_lazy_growths_total",
                    "kv_pages_lazy_grown_total",
                    "kv_preempt_exhaustion_total",
                    "mesh", "mesh_devices",
                    "step_device_seconds_total",
                    "step_wall_seconds_total", "step_device_share",
                    "spec_rounds_total", "spec_drafted_total",
                    "spec_accepted_total", "spec_accept_buckets",
                    "spec_accept_hist", "spec_accept_sum",
                    "spec_accept_count") if k in engine},
                **self.extra_info}

    def metrics_text(self) -> str:
        """Prometheus text exposition of the serving counters —
        the observability surface a scraping stack expects from an
        in-cluster `V1Service` (SURVEY §5.5).  Includes the
        per-request queue/prefill/decode phase breakdown (summaries)
        and the continuous-batching engine gauges."""
        # One rejection counter, owned by the admission queue (bumped
        # in submit) — the HTTP 429 path and in-process callers both
        # land there, so /metrics and /info can never disagree.
        es = self.engine.stats() if self.engine is not None else {}
        rejected = es.get("rejected_total", 0)
        stalls = self.watchdog.stalls_total \
            if self.watchdog is not None else 0
        with self._stats_lock:
            lat_sum, lat_count = self._lat_sum, self._lat_count
            toks, errs = self._tokens_out, self.errors
            q_sum, p_sum, d_sum, bd_count = (
                self._queue_s_sum, self._prefill_s_sum,
                self._decode_s_sum, self._breakdown_count)
        lines = [
            "# TYPE ptpu_serving_requests_total counter",
            f"ptpu_serving_requests_total {self.requests}",
            "# TYPE ptpu_serving_errors_total counter",
            f"ptpu_serving_errors_total {errs}",
            "# TYPE ptpu_serving_rejected_total counter",
            f"ptpu_serving_rejected_total {rejected}",
            "# TYPE ptpu_serving_tokens_generated_total counter",
            f"ptpu_serving_tokens_generated_total {toks}",
            "# TYPE ptpu_serving_coalesced_batches_total counter",
            f"ptpu_serving_coalesced_batches_total "
            f"{self.coalesced_batches}",
            "# TYPE ptpu_serving_coalesced_requests_total counter",
            f"ptpu_serving_coalesced_requests_total "
            f"{self.coalesced_requests}",
            "# TYPE ptpu_serving_request_seconds summary",
            f"ptpu_serving_request_seconds_sum {lat_sum:.6f}",
            f"ptpu_serving_request_seconds_count {lat_count}",
            # Phase breakdown: queue (waiting for prefill/device),
            # prefill (prompt consumption), decode (token generation).
            "# TYPE ptpu_serving_queue_seconds summary",
            f"ptpu_serving_queue_seconds_sum {q_sum:.6f}",
            f"ptpu_serving_queue_seconds_count {bd_count}",
            "# TYPE ptpu_serving_prefill_seconds summary",
            f"ptpu_serving_prefill_seconds_sum {p_sum:.6f}",
            f"ptpu_serving_prefill_seconds_count {bd_count}",
            "# TYPE ptpu_serving_decode_seconds summary",
            f"ptpu_serving_decode_seconds_sum {d_sum:.6f}",
            f"ptpu_serving_decode_seconds_count {bd_count}",
            "# TYPE ptpu_serving_compiled_programs gauge",
            f"ptpu_serving_compiled_programs {len(self._fns)}",
            "# TYPE ptpu_serving_prefix_hits_total counter",
            f"ptpu_serving_prefix_hits_total {self.prefix_hits}",
            "# TYPE ptpu_serving_prefix_entries gauge",
            f"ptpu_serving_prefix_entries {len(self._prefix)}",
            # Prefix-reuse in TOKENS: prompt tokens served from a
            # stored prefill instead of fresh prefill work (the
            # shared-prefix bench leg's assertion target).
            "# TYPE ptpu_serving_prefix_hit_tokens_total counter",
            f"ptpu_serving_prefix_hit_tokens_total "
            f"{self.prefix_hit_tokens}",
            # 503s shed at the drain gate (before the engine sees the
            # request) — every batching mode has this path, so it is
            # a server counter, not an engine one.
            "# TYPE ptpu_serving_drain_rejected_total counter",
            f"ptpu_serving_drain_rejected_total "
            f"{self.drain_rejected}",
            # Request-history ring occupancy (GET /requests): how
            # many terminal records are retained vs the capacity
            # knob, and how many have rolled off the ring.
            "# TYPE ptpu_serving_request_records gauge",
            f"ptpu_serving_request_records {len(self.history)}",
            "# TYPE ptpu_serving_request_records_evicted_total "
            "counter",
            f"ptpu_serving_request_records_evicted_total "
            f"{self.history.evicted_total}",
            # Stall-watchdog fires (0 and absent-watchdog both read
            # 0, so dashboards can alert on any increase without
            # caring whether the knob is armed).
            "# TYPE ptpu_serving_stalls_total counter",
            f"ptpu_serving_stalls_total {stalls}",
        ]
        # Recompile sentinel (analysis/recompile.py): ONE counter set
        # across the server/engine/slot program caches, rendered by
        # the shared telemetry helper (same module as the histogram
        # exposition, so /metrics and /info can never drift).
        lines += render_compile_cache(self.recompile.snapshot())
        if self.recorder is not None:
            # Flight-recorder attribution gauges (collective/host-gap/
            # device-busy shares + serving MFU): rendered from the
            # SAME record GET /profile/report returns — one
            # reduction, no drift (serving/profiling.py).
            lines += self.recorder.metrics_lines()
        # Latency histograms (queue-wait, prefill, decode-per-token,
        # TTFT, total) — rendered by the same telemetry helper as the
        # spec-acceptance histogram below, so every histogram on this
        # endpoint shares one exposition path.
        lines += self.telemetry.metrics_lines()
        # Per-phase forensics families (serving/forensics.py):
        # cumulative seconds + wall share per ledger phase, and the
        # sentry's anomaly counter — labeled families whose TYPE
        # lines render unconditionally, so the fleet federation sees
        # them before first traffic.
        if self.forensics is not None:
            lines += self.forensics.metrics_lines("ptpu_serving")
        if self.engine is not None:
            lines += [
                "# TYPE ptpu_serving_slots gauge",
                f"ptpu_serving_slots {es['slots']}",
                "# TYPE ptpu_serving_slots_active gauge",
                f"ptpu_serving_slots_active {es['slots_active']}",
                # resident/total as a ready-made 0..1 ratio, so pool
                # utilization under mixed load needs no PromQL join
                "# TYPE ptpu_serving_slot_occupancy gauge",
                f"ptpu_serving_slot_occupancy {es['slot_occupancy']}",
                "# TYPE ptpu_serving_queue_len gauge",
                f"ptpu_serving_queue_len {es['queue_len']}",
                "# TYPE ptpu_serving_queue_depth gauge",
                f"ptpu_serving_queue_depth {es['queue_depth']}",
                "# TYPE ptpu_serving_admitted_total counter",
                f"ptpu_serving_admitted_total {es['admitted_total']}",
                # admissions/completions split by decode mode: how
                # much of the pool mixed traffic actually gives to
                # sampled streams
                "# TYPE ptpu_serving_admitted_greedy_total counter",
                f"ptpu_serving_admitted_greedy_total "
                f"{es['admitted_greedy_total']}",
                "# TYPE ptpu_serving_admitted_sampled_total counter",
                f"ptpu_serving_admitted_sampled_total "
                f"{es['admitted_sampled_total']}",
                "# TYPE ptpu_serving_admitted_spec_total counter",
                f"ptpu_serving_admitted_spec_total "
                f"{es['admitted_spec_total']}",
                "# TYPE ptpu_serving_completed_total counter",
                f"ptpu_serving_completed_total "
                f"{es['completed_total']}",
                "# TYPE ptpu_serving_completed_greedy_total counter",
                f"ptpu_serving_completed_greedy_total "
                f"{es['completed_greedy_total']}",
                "# TYPE ptpu_serving_completed_sampled_total counter",
                f"ptpu_serving_completed_sampled_total "
                f"{es['completed_sampled_total']}",
                "# TYPE ptpu_serving_completed_spec_total counter",
                f"ptpu_serving_completed_spec_total "
                f"{es['completed_spec_total']}",
                # Request lifecycle: terminal-status counters, the
                # preempt/resume pair, the per-class splits, and the
                # drain latch — all from the same engine.stats()
                # dict /info reports.
                "# TYPE ptpu_serving_cancelled_total counter",
                f"ptpu_serving_cancelled_total "
                f"{es['cancelled_total']}",
                "# TYPE ptpu_serving_deadline_expired_total counter",
                f"ptpu_serving_deadline_expired_total "
                f"{es['expired_total']}",
                "# TYPE ptpu_serving_shed_total counter",
                f"ptpu_serving_shed_total {es['shed_total']}",
                "# TYPE ptpu_serving_shed_interactive_total counter",
                f"ptpu_serving_shed_interactive_total "
                f"{es['shed_interactive_total']}",
                "# TYPE ptpu_serving_shed_batch_total counter",
                f"ptpu_serving_shed_batch_total "
                f"{es['shed_batch_total']}",
                "# TYPE ptpu_serving_preempted_total counter",
                f"ptpu_serving_preempted_total "
                f"{es['preempted_total']}",
                "# TYPE ptpu_serving_resumed_total counter",
                f"ptpu_serving_resumed_total {es['resumed_total']}",
                # Page-shed and exhaustion-preempt counters live in
                # engine.stats() on EVERY layout (0 on fixed lanes),
                # so they render unconditionally — the structural
                # no-drift walk covers fixed-lane servers too.
                "# TYPE ptpu_serving_shed_kv_pages_total counter",
                f"ptpu_serving_shed_kv_pages_total "
                f"{es['shed_kv_pages_total']}",
                "# TYPE ptpu_serving_kv_preempt_exhaustion_total "
                "counter",
                f"ptpu_serving_kv_preempt_exhaustion_total "
                f"{es['kv_preempt_exhaustion_total']}",
                # Fault tolerance (serving/faults.py + recovery.py):
                # step retries, requeue-and-resume events, quarantine
                # convictions, supervised crash/restart totals, the
                # breaker gauge, and the per-site injected-fault
                # split — all from the same engine.stats() dict
                # /info reports (no-drift pin, tests/test_faults.py).
                "# TYPE ptpu_serving_step_retries_total counter",
                f"ptpu_serving_step_retries_total "
                f"{es['step_retries_total']}",
                "# TYPE ptpu_serving_requests_requeued_total counter",
                f"ptpu_serving_requests_requeued_total "
                f"{es['requests_requeued_total']}",
                "# TYPE ptpu_serving_poisoned_total counter",
                f"ptpu_serving_poisoned_total "
                f"{es['poisoned_total']}",
                "# TYPE ptpu_serving_telemetry_errors_total counter",
                f"ptpu_serving_telemetry_errors_total "
                f"{es['telemetry_errors_total']}",
                "# TYPE ptpu_serving_engine_crashes_total counter",
                f"ptpu_serving_engine_crashes_total "
                f"{es['engine_crashes_total']}",
                "# TYPE ptpu_serving_engine_restarts_total counter",
                f"ptpu_serving_engine_restarts_total "
                f"{es['engine_restarts_total']}",
                "# TYPE ptpu_serving_engine_down gauge",
                f"ptpu_serving_engine_down "
                f"{1 if es['engine_down'] else 0}",
                "# TYPE ptpu_serving_breaker_open gauge",
                f"ptpu_serving_breaker_open "
                f"{1 if es['breaker_state'] == 'open' else 0}",
                "# TYPE ptpu_serving_faults_injected_total counter",
                *[f'ptpu_serving_faults_injected_total'
                  f'{{site="{site}"}} {n}'
                  for site, n in sorted(
                      es["faults_injected"].items())],
                "# TYPE ptpu_serving_prefix_store_errors_total "
                "counter",
                f"ptpu_serving_prefix_store_errors_total "
                f"{self._prefix_store_errors}",
                "# TYPE ptpu_serving_admitted_interactive_total "
                "counter",
                f"ptpu_serving_admitted_interactive_total "
                f"{es['admitted_interactive_total']}",
                "# TYPE ptpu_serving_admitted_batch_total counter",
                f"ptpu_serving_admitted_batch_total "
                f"{es['admitted_batch_total']}",
                "# TYPE ptpu_serving_queue_len_interactive gauge",
                f"ptpu_serving_queue_len_interactive "
                f"{es['queue_len_interactive']}",
                "# TYPE ptpu_serving_queue_len_batch gauge",
                f"ptpu_serving_queue_len_batch "
                f"{es['queue_len_batch']}",
                "# TYPE ptpu_serving_draining gauge",
                f"ptpu_serving_draining "
                f"{1 if es['draining'] else 0}",
                "# TYPE ptpu_serving_evicted_total counter",
                f"ptpu_serving_evicted_total {es['evicted_total']}",
                "# TYPE ptpu_serving_decode_steps_total counter",
                f"ptpu_serving_decode_steps_total "
                f"{es['decode_steps_total']}",
                "# TYPE ptpu_serving_prefill_chunks_total counter",
                f"ptpu_serving_prefill_chunks_total "
                f"{es['prefill_chunks_total']}",
                # Speculative scheduling counters + the per-request
                # acceptance-rate histogram — rendered from the SAME
                # engine.stats() dict /info reports, so the two
                # endpoints can never drift.
                "# TYPE ptpu_serving_spec_rounds_total counter",
                f"ptpu_serving_spec_rounds_total "
                f"{es['spec_rounds_total']}",
                "# TYPE ptpu_serving_spec_drafted_total counter",
                f"ptpu_serving_spec_drafted_total "
                f"{es['spec_drafted_total']}",
                "# TYPE ptpu_serving_spec_accepted_total counter",
                f"ptpu_serving_spec_accepted_total "
                f"{es['spec_accepted_total']}",
            ]
            if "mesh" in es:
                # Mesh topology + the per-step device-share counters
                # (meshed engines only).  Axis sizes render as one
                # labeled gauge per active axis; the step counters
                # feed the bench's tp=1-vs-tpN collective-share
                # derivation (see engine.stats()).
                lines += [
                    "# TYPE ptpu_serving_mesh_devices gauge",
                    f"ptpu_serving_mesh_devices {es['mesh_devices']}",
                    "# TYPE ptpu_serving_mesh_axis_size gauge",
                ]
                for axis, size in sorted(es["mesh"]["axes"].items()):
                    lines.append(
                        f'ptpu_serving_mesh_axis_size{{axis="{axis}"}}'
                        f' {size}')
                lines += [
                    "# TYPE ptpu_serving_step_device_seconds_total "
                    "counter",
                    f"ptpu_serving_step_device_seconds_total "
                    f"{es['step_device_seconds_total']}",
                    "# TYPE ptpu_serving_step_wall_seconds_total "
                    "counter",
                    f"ptpu_serving_step_wall_seconds_total "
                    f"{es['step_wall_seconds_total']}",
                    "# TYPE ptpu_serving_step_device_share gauge",
                    f"ptpu_serving_step_device_share "
                    f"{es['step_device_share'] or 0}",
                ]
            if "kv_pages" in es:
                # Paged-KV page-pool gauges (kv_paged engines only):
                # the occupancy surface the block-table refactor
                # exists for, plus the can-never-fit shed split.
                lines += [
                    "# TYPE ptpu_serving_kv_pages gauge",
                    f"ptpu_serving_kv_pages {es['kv_pages']}",
                    "# TYPE ptpu_serving_kv_page_tokens gauge",
                    f"ptpu_serving_kv_page_tokens "
                    f"{es['kv_page_tokens']}",
                    "# TYPE ptpu_serving_kv_pages_free gauge",
                    f"ptpu_serving_kv_pages_free "
                    f"{es['kv_pages_free']}",
                    "# TYPE ptpu_serving_kv_pages_resident gauge",
                    f"ptpu_serving_kv_pages_resident "
                    f"{es['kv_pages_resident']}",
                    "# TYPE ptpu_serving_kv_pages_shared gauge",
                    f"ptpu_serving_kv_pages_shared "
                    f"{es['kv_pages_shared']}",
                    # Tiered KV memory (PR 12): lazy growth/preempt
                    # counters from the same engine.stats() dict, and
                    # the host-spill tier's gauges from ONE
                    # _spill_stats() dict shared with /info.
                    "# TYPE ptpu_serving_kv_lazy gauge",
                    f"ptpu_serving_kv_lazy "
                    f"{1 if es['kv_lazy'] else 0}",
                    "# TYPE ptpu_serving_kv_pages_lazy_growths_total "
                    "counter",
                    f"ptpu_serving_kv_pages_lazy_growths_total "
                    f"{es['kv_pages_lazy_growths_total']}",
                    "# TYPE ptpu_serving_kv_pages_lazy_grown_total "
                    "counter",
                    f"ptpu_serving_kv_pages_lazy_grown_total "
                    f"{es['kv_pages_lazy_grown_total']}",
                ]
                sp = self._spill_stats()
                lines += [
                    "# TYPE ptpu_serving_kv_host_spill_bytes gauge",
                    f"ptpu_serving_kv_host_spill_bytes "
                    f"{sp['kv_host_spill_bytes']}",
                    "# TYPE ptpu_serving_kv_host_entries gauge",
                    f"ptpu_serving_kv_host_entries "
                    f"{sp['kv_host_entries']}",
                    "# TYPE ptpu_serving_kv_host_spills_total counter",
                    f"ptpu_serving_kv_host_spills_total "
                    f"{sp['kv_host_spills_total']}",
                    "# TYPE ptpu_serving_kv_rematerialize_hits_total "
                    "counter",
                    f"ptpu_serving_kv_rematerialize_hits_total "
                    f"{sp['kv_rematerialize_hits_total']}",
                    "# TYPE ptpu_serving_kv_rematerialize_bytes_total "
                    "counter",
                    f"ptpu_serving_kv_rematerialize_bytes_total "
                    f"{sp['kv_rematerialize_bytes_total']}",
                    "# TYPE ptpu_serving_kv_host_dropped_total "
                    "counter",
                    f"ptpu_serving_kv_host_dropped_total "
                    f"{sp['kv_host_dropped_total']}",
                    "# TYPE ptpu_serving_kv_promotions_total counter",
                    f"ptpu_serving_kv_promotions_total "
                    f"{sp['kv_promotions_total']}",
                    "# TYPE ptpu_serving_prefix_fetch_total counter",
                    f"ptpu_serving_prefix_fetch_total "
                    f"{sp['prefix_fetch_total']}",
                    "# TYPE ptpu_serving_prefix_fetch_hits_total "
                    "counter",
                    f"ptpu_serving_prefix_fetch_hits_total "
                    f"{sp['prefix_fetch_hits_total']}",
                    "# TYPE ptpu_serving_prefix_fetch_bytes_total "
                    "counter",
                    f"ptpu_serving_prefix_fetch_bytes_total "
                    f"{sp['prefix_fetch_bytes_total']}",
                    # The TYPE line renders even with no failures yet
                    # — scrapers (and the no-drift walk) see the
                    # family exists before its first labeled sample.
                    "# TYPE ptpu_serving_prefix_fetch_failed_total "
                    "counter",
                ]
                lines += [
                    f"ptpu_serving_prefix_fetch_failed_total"
                    f'{{reason="{r}"}} {n}'
                    for r, n in sorted(
                        sp["prefix_fetch_failed"].items())
                ]
                lines += [
                    "# TYPE ptpu_serving_prefix_ingest_total counter",
                    f"ptpu_serving_prefix_ingest_total "
                    f"{sp['prefix_ingest_total']}",
                    "# TYPE ptpu_serving_prefix_ingest_rejected_total "
                    "counter",
                    f"ptpu_serving_prefix_ingest_rejected_total "
                    f"{sp['prefix_ingest_rejected_total']}",
                    "# TYPE ptpu_serving_prefix_handoff_entries_total "
                    "counter",
                    f"ptpu_serving_prefix_handoff_entries_total "
                    f"{sp['prefix_handoff_entries_total']}",
                    "# TYPE ptpu_serving_prefix_handoff_bytes_total "
                    "counter",
                    f"ptpu_serving_prefix_handoff_bytes_total "
                    f"{sp['prefix_handoff_bytes_total']}",
                    "# TYPE ptpu_serving_prefix_handoff_failed_total "
                    "counter",
                    f"ptpu_serving_prefix_handoff_failed_total "
                    f"{sp['prefix_handoff_failed_total']}",
                    "# TYPE ptpu_serving_prefix_evict_hints_total "
                    "counter",
                    f"ptpu_serving_prefix_evict_hints_total "
                    f"{sp['prefix_evict_hints_total']}",
                ]
            # The acceptance-rate histogram renders through the SAME
            # shared helper as the latency histograms, from the same
            # engine.stats() dict /info reports.
            lines += render_histogram(
                "ptpu_serving_spec_accept_rate",
                es["spec_accept_buckets"], es["spec_accept_hist"],
                es["spec_accept_sum"], es["spec_accept_count"])
        return "\n".join(lines) + "\n"


def _disconnect_probe(conn):
    """A zero-cost poll for "is the client still there?" used while a
    handler thread waits on an engine group: after the request body,
    a well-behaved client sends NOTHING until the response — so a
    readable socket whose peek returns b"" means the peer closed.
    (A pipelined second request also reads as readable; its non-empty
    peek keeps the request alive, which is the conservative side.)

    Known limitation: a client HALF-close (``shutdown(SHUT_WR)``
    after the body, still reading) is indistinguishable from a full
    close at this API — its request is cancelled too.  That matches
    the common async-server convention (an empty read IS "client
    disconnected"), and half-closing writers mid-request are rare
    enough that reclaiming the slot wins; a client that wants the
    response must keep its write side open."""
    def check() -> bool:
        try:
            # poll(), not select(): select is FD_SETSIZE-bound, so
            # at ~1024+ open fds (many waiting clients) it raises
            # ValueError for high-numbered connections — which the
            # except branch would misread as "client gone" and
            # spuriously cancel live requests.  poll has no fd
            # limit; ValueError now only means a genuinely closed
            # socket (fileno() == -1).
            p = select.poll()
            p.register(conn.fileno(), select.POLLIN)
            if not p.poll(0):
                return False
            return conn.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True     # probe failed: the socket is gone
    return check


class _ServingHTTPServer(ThreadingHTTPServer):
    # Stdlib default backlog is 5: a burst of concurrent clients
    # beyond it hits kernel SYN retransmits (~1s latency spikes that
    # look like serving stalls).  The admission queue, not the listen
    # backlog, is the intended backpressure surface.
    request_queue_size = 128
    daemon_threads = True


def make_server(host: str, port: int, ms: ModelServer
                ) -> ThreadingHTTPServer:
    return _ServingHTTPServer((host, port), make_handler(ms))


def make_handler(ms: ModelServer):
    """The request-handler CLASS for ``ms`` (what ``make_server``
    binds).  Exposed separately so the router tier's in-process
    replicas (serving/router.py LocalReplica) can mount the same
    handler on their chaos-capable HTTP server."""
    class Handler(BaseHTTPRequestHandler):
        def _req_id(self) -> str:
            """This request's correlation ID: the inbound
            ``X-Request-Id`` when usable, else generated.  Called at
            the top of every do_* (handler instances serve multiple
            keep-alive requests, so the field must refresh per
            request); ``_send_raw`` echoes it on EVERY response —
            success, 4xx, and 5xx alike."""
            rid = sanitize_request_id(
                self.headers.get("X-Request-Id"))
            self._rid = rid or new_request_id()
            return self._rid

        def _send_raw(self, code: int, body: bytes, ctype: str,
                      extra=None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            rid = getattr(self, "_rid", None)
            if rid is None:
                rid = self._rid = new_request_id()
            self.send_header("X-Request-Id", rid)
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send(self, code: int, obj: Dict[str, Any],
                  extra=None) -> None:
            self._send_raw(code, json.dumps(obj).encode(),
                           "application/json", extra)

        def log_message(self, fmt, *args):
            # Quiet by default; the structured per-request access log
            # (ms.log_access, --access-log) replaces this — the
            # stdlib's format can't carry status/kind/tokens/latency.
            pass

        def do_GET(self):
            self._req_id()
            path = urlparse(self.path).path
            if path == "/requests" or path.startswith("/requests/") \
                    or path == "/debug/state":
                self._do_debug_get(path)
                return
            if self.path == "/healthz":
                # Readiness doubles as the router's drain signal: a
                # draining server answers 503 so load balancers stop
                # routing here while in-flight work finishes — and a
                # breaker-open engine answers 503 ``engine_down`` so
                # the router sheds AROUND a crash-storming replica
                # instead of feeding it work it will hang.
                # ONE machine-readable schema for every not-ready
                # path: {"status": "unavailable", "reason": ...} —
                # the router probe parses a single contract whether
                # the replica is draining or breaker-open (pinned in
                # tests/test_serving_smoke.py + tests/test_faults.py;
                # extras ride behind the two fixed keys).
                if ms.draining:
                    self._send(503, {"status": "unavailable",
                                     "reason": "draining",
                                     "model": ms.model_name,
                                     **ms.drain_status()})
                elif ms.engine is not None and ms.engine.down:
                    self._send(503, {
                        "status": "unavailable",
                        "reason": "engine_down",
                        "model": ms.model_name,
                        **({"supervisor": ms.supervisor.status()}
                           if ms.supervisor is not None else {})})
                else:
                    # ``role`` rides the 200 body so the router's
                    # probe loop learns the fleet's prefill/decode
                    # split without an extra /info round trip;
                    # ``t`` (host wall clock at response build) is
                    # the router's clock-skew ESTIMATE input — a
                    # host-clock reading, never device truth
                    # (docs/DESIGN.md time-truth discipline).
                    self._send(200, {"status": "ok",
                                     "model": ms.model_name,
                                     "role": ms.role,
                                     "t": time.time()})
            elif self.path == "/info":
                self._send(200, ms.info())
            elif self.path == "/metrics":
                self._send_raw(200, ms.metrics_text().encode(),
                               "text/plain; version=0.0.4")
            elif self.path == "/trace":
                # Chrome trace-event JSON: request spans + the engine
                # step timeline, loadable directly in Perfetto /
                # chrome://tracing (docs/SERVING.md).
                self._send(200, ms.telemetry.chrome_trace())
            elif self.path == "/anomalies":
                # The anomaly sentry's ranked findings + baselines
                # (serving/forensics.py; docs/SERVING.md
                # "Tail-latency forensics").
                if ms.forensics is None:
                    self._send(400, {
                        "error": "forensics disabled (start the "
                                 "server with forensics enabled)"})
                else:
                    self._send(200, ms.forensics.report())
            elif self.path == "/debug/exemplars":
                # Per-bucket request-ID exemplars for every latency
                # histogram — the full K retained per bucket (the
                # /metrics exposition carries only the latest).
                self._send(200, ms.telemetry.exemplars_report())
            elif self.path == "/profile/report":
                # The flight recorder's parsed attribution for the
                # most recent profiled window(s) — the same numbers
                # the /metrics gauges export (one reduction).
                if ms.recorder is None:
                    self._send(400, {
                        "error": "flight recorder disabled (start "
                                 "the server with --profile-every N "
                                 "and --profile-dir)"})
                else:
                    rep = ms.recorder.report()
                    if rep["latest"] is None:
                        self._send(404, {
                            "error": "no profiled window analyzed "
                                     "yet",
                            **{k: rep[k] for k in
                               ("windows_total", "windows_skipped",
                                "windows_deferred", "last_error")}})
                    else:
                        self._send(200, rep)
            elif self.path == "/prefix/index":
                # Fleet inventory: stable entry keys + tier/hits so
                # the router's one-copy-somewhere pass can plan
                # evictions without pulling any payload.
                if not ms.kv_paged:
                    self._send(400, {
                        "error": "prefix index requires a paged "
                                 "engine (--kv-paged)"})
                else:
                    self._send(200, ms.prefix_index())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def _do_debug_get(self, path: str):
            """The request-scoped debuggability surface:

            - ``GET /debug/state`` — the engine's latest published
              step-boundary snapshot + server lifecycle state.
              Served from the SnapshotBoard, never the device lock
              (SNAPSHOT-LOCK, docs/DESIGN.md), so it answers even
              while the engine is wedged inside a device call.
            - ``GET /requests?status=...&limit=N`` — newest-first
              summaries from the terminal-record retention ring.
            - ``GET /requests/<id>`` — one request's full causal
              record (timeline, preemptions + preemptor IDs, page
              waits, prefix provenance, terminal cause)."""
            if path == "/debug/state":
                self._send(200, ms.debug_state())
                return
            if not ms.history.enabled:
                self._send(400, {
                    "error": "request history disabled (start the "
                             "server with --request-history N)"})
                return
            if path in ("/requests", "/requests/"):
                q = parse_qs(urlparse(self.path).query)
                status = (q.get("status") or [None])[0]
                try:
                    limit = int((q.get("limit") or ["100"])[0])
                except ValueError:
                    self._send(400,
                               {"error": "limit must be an int"})
                    return
                self._send(200, {
                    "requests": ms.history.list(status=status,
                                                limit=limit),
                    **ms.history.stats()})
                return
            want = path[len("/requests/"):]
            rec = ms.history.get(want)
            if rec is None:
                self._send(404, {
                    "error": f"no record for request {want!r} "
                             f"(never seen, or rolled off the "
                             f"{ms.history.capacity}-record "
                             f"retention ring)"})
            else:
                self._send(200, rec)

        def _do_profile(self):
            """POST /profile/start|stop: guarded single-flight
            jax.profiler wrap.  400 when the server was started
            without --profile-dir (profiling writes device traces to
            disk — explicit opt-in); 409 on state conflicts (second
            start, stop with nothing running)."""
            t0 = time.perf_counter()
            if ms.profiler is None:
                code, resp = 400, {
                    "error": "profiling disabled (start the server "
                             "with --profile-dir)"}
            else:
                try:
                    if self.path == "/profile/start":
                        d = ms.profiler.start()
                        code, resp = 200, {"profiling": True,
                                           "dir": d}
                    else:
                        d = ms.profiler.stop()
                        code, resp = 200, {"profiling": False,
                                           "dir": d}
                except RuntimeError as e:
                    code, resp = 409, {"error": str(e)}
                except Exception as e:
                    code, resp = 500, {
                        "error": f"{type(e).__name__}: {e}"}
            try:
                self._send(code, resp)
            except OSError:
                pass
            ms.log_access("POST", self.path, code, None, resp,
                          time.perf_counter() - t0,
                          rid=getattr(self, "_rid", None))

        def _do_prefix(self, rid: str) -> None:
            """The fleet prefix cache's wire surface:

            - ``POST /prefix/fetch``  — serve a stored entry,
              serialized + checksummed (404 = holder miss).
            - ``POST /prefix/ingest`` — verify + admit one wire
              payload into the host tier (drain handoff's push).
            - ``POST /prefix/handoff`` — push this replica's entries
              to a successor (the router posts this mid-drain).
            - ``POST /prefix/evict``  — apply fleet eviction hints
              (host-tier only).

            All answer while DRAINING — the drain window is when the
            fleet needs this surface most."""
            t0 = time.perf_counter()
            req = None
            try:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                if self.path == "/prefix/fetch":
                    req = json.loads(raw or b"{}")
                    blob = ms.prefix_wire_payload(req)
                    if blob is None:
                        code, resp = 404, {"error": "prefix not held "
                                                    "here"}
                    else:
                        self._send_raw(200, blob,
                                       "application/octet-stream")
                        ms.log_access("POST", self.path, 200, req,
                                      {"nbytes": len(blob)},
                                      time.perf_counter() - t0,
                                      rid=rid)
                        return
                elif self.path == "/prefix/ingest":
                    # Body IS the wire payload (octet-stream, not
                    # JSON) — checksum verified inside.
                    req = {"nbytes": len(raw)}
                    code, resp = 200, ms.prefix_ingest(raw)
                elif self.path == "/prefix/handoff":
                    req = json.loads(raw or b"{}")
                    code, resp = 200, ms.prefix_handoff(req)
                elif self.path == "/prefix/evict":
                    req = json.loads(raw or b"{}")
                    code, resp = 200, ms.prefix_evict(req)
                else:
                    code, resp = 404, {"error":
                                       f"no route {self.path}"}
            except WirePayloadError as e:
                # Typed integrity failure: the payload never touched
                # the cache (counted prefix_ingest_rejected_total).
                code, resp = 400, {"error": str(e),
                                   "reason": "payload_integrity"}
            except ValueError as e:
                code, resp = 400, {"error": str(e)}
            except Exception as e:  # never kill the server thread
                code, resp = 500, {"error":
                                   f"{type(e).__name__}: {e}"}
            if isinstance(resp, dict):
                resp.setdefault("request_id", rid)
            try:
                self._send(code, resp)
            except OSError:
                pass
            ms.log_access("POST", self.path, code, req, resp,
                          time.perf_counter() - t0, rid=rid)

        def do_POST(self):
            rid = self._req_id()
            if self.path in ("/profile/start", "/profile/stop"):
                self._do_profile()
                return
            if self.path == "/drain":
                # Stop admission, finish in-flight, readiness off —
                # idempotent, so an orchestrator can post it again
                # while polling the in-flight snapshot toward zero.
                t0 = time.perf_counter()
                resp = ms.drain()
                try:
                    self._send(200, resp)
                except OSError:
                    pass
                ms.log_access("POST", self.path, 200, None, resp,
                              time.perf_counter() - t0, rid=rid)
                return
            if self.path.startswith("/prefix/"):
                self._do_prefix(rid)
                return
            if self.path not in ("/generate", "/prefill"):
                self._send(404, {"error": f"no route {self.path}"})
                return
            # Generate FIRST, send after: a client hanging up while a
            # successful response streams out must not count as a
            # serving error (nor trigger a doomed second send).
            extra = None
            t0 = time.perf_counter()
            req = None
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/generate":
                    # The disconnect probe lets a vanished client's
                    # request cancel at the next step boundary
                    # instead of decoding to budget exhaustion.
                    code, resp = 200, ms.generate(
                        req,
                        cancel_check=_disconnect_probe(
                            self.connection),
                        rid=rid)
                else:
                    code, resp = 200, ms.prefill_prompt(req)
            except ShedError as e:
                # Graceful overload: 503 with a machine-readable
                # reason (queue_deadline / draining /
                # request_timeout) so clients and routers can tell
                # shed classes apart from hard failures.
                code = 503
                resp = {"error": str(e), "reason": e.reason}
                if e.retry_after:
                    extra = {"Retry-After": str(e.retry_after)}
            except DeadlineExceeded as e:
                code, resp = 504, {"error": str(e),
                                   "reason": "deadline"}
            except RequestCancelled as e:
                # 499 (client closed request): almost always
                # unsendable — the client is gone — but the access
                # log line is the point.
                code, resp = 499, {"error": str(e),
                                   "reason": "cancelled"}
            except QueueFullError as e:
                # Explicit backpressure, not an error: the bounded
                # admission queue is full — shed load with the
                # standard retry contract instead of letting handler
                # threads pile up behind the engine.  The rejection
                # was already counted by AdmissionQueue.submit.
                code = 429
                resp = {"error": str(e),
                        "retry_after": e.retry_after}
                extra = {"Retry-After": str(e.retry_after)}
            except PoisonedRequest as e:
                # Quarantine conviction: THIS request's computation
                # kept failing the shared decode step, so it alone
                # fails — typed, with the machine-readable reason,
                # while its co-tenants resumed token-identically
                # (engine._quarantine_step).
                with ms._stats_lock:
                    ms.errors += 1
                code, resp = 500, {"error": str(e),
                                   "reason": e.reason}
            except ValueError as e:
                with ms._stats_lock:
                    ms.errors += 1
                code, resp = 400, {"error": str(e)}
            except Exception as e:  # never kill the server thread
                with ms._stats_lock:
                    ms.errors += 1
                code, resp = 500, {"error": f"{type(e).__name__}: {e}"}
            # Error bodies carry the ID too (the header already
            # does): a client that only kept the JSON can still
            # quote the correlation key in a bug report.
            if isinstance(resp, dict):
                resp.setdefault("request_id", rid)
            try:
                if ms.faults is not None:
                    # Injected handler-socket death at the worst
                    # moment — the response write.  The connection
                    # drops with no response; server-side state is
                    # already terminal, which is exactly what the
                    # chaos harness verifies (no leaked slot, no
                    # wedged worker, counters still advance).
                    ms.faults.check("socket_reset")
                self._send(code, resp, extra)
            except SocketReset:
                self.close_connection = True
                try:
                    self.connection.close()
                except OSError:
                    pass
            except OSError:
                pass  # client went away mid-write; nothing to do
            # AFTER the send, so logging latency never delays the
            # response; 4xx/5xx lines are the whole point (failed
            # requests used to vanish into the log_message no-op).
            ms.log_access("POST", self.path, code, req, resp,
                          time.perf_counter() - t0, rid=rid)
            # Front-end history record for requests the engine never
            # recorded (validation 400s, sheds, solo paths) — the
            # engine's full causal record wins when both exist.
            ms.record_front(rid, self.path, code, req, resp)

    return Handler
