"""Paged KV memory for the continuous-batching engine.

The fixed-lane pool (slots.py) stacks one FULL-WIDTH cache lane per
slot, so every resident request pays for ``max_position`` tokens of
KV whatever its actual length: occupancy collapses under mixed
short/long traffic and max concurrency is pinned by the widest
request, not by token usage.  This module replaces that storage with
BLOCK-TABLE PAGING — the VirtualFlow decoupling (arXiv:2009.09523) of
logical slots from physical cache layout:

- every position-indexed cache leaf is stored as a POOL of fixed-size
  pages (``page_tokens`` positions each, leaf shape ``lead +
  (n_pages, page_tokens) + rest``);
- each slot owns a PAGE TABLE (padded int32 page-id list, a RUNTIME
  argument of the step programs, so one compiled program per
  (window, pages-per-slot-pad) shape serves every occupancy pattern
  — the zero-steady-state-recompile contract holds per pad class,
  never per request mix);
- the step programs GATHER a slot's pages into a position-contiguous
  view (``models/kv_cache.gather_pages``), run the SAME decode bodies
  the fixed-lane manager runs (slots.build_step_body /
  build_spec_step_body — one traced body, two storage layouts), and
  SCATTER only the window's dirty pages back;
- pages are REFERENCE-COUNTED and shared COPY-ON-WRITE: a stored
  prefix's pages map read-only into every matching slot's table
  (admission of a prefix hit costs only the divergent suffix), and a
  page is never a scatter target while shared — dirty windows only
  ever cover pages the slot privately owns, enforced by construction
  (decode writes start at the prompt end, which is at or past the
  shared-aligned boundary) rather than by a runtime branch.

Safety argument, same shape as the fixed-lane one: a slot's
materialized view is position-contiguous (page i covers absolute
positions [i*pt, (i+1)*pt)), so the causal-append masking, chunked
prefill, and the speculative rollback contract (stale entries masked
by absolute position) hold verbatim on paged storage — rollback is
still just a ``cache_index`` rewind inside the step body, with NO
page bookkeeping, because each slot's pages are reserved up front for
its full budget (see below).  Idle slots' dead stepping lands in a
per-slot SCRATCH page, and writes redirected away from shared pages
land in a single TRASH page; both hold garbage by definition and are
masked by position before any query could admit them.

RESERVATION DISCIPLINE (two modes):

- FULL (default): admission reserves a request's FULL page need
  (prompt + budget + speculative slack) minus its shared prefix
  pages.  Deadlock-free by construction — a resident can always
  finish — and spec rollback stays pure, because no mid-decode page
  event exists.  Page exhaustion only exists at the edges: a request
  that can NEVER fit the pool sheds 503 ``reason: kv_pages`` at
  submit, and one that doesn't fit RIGHT NOW waits admit-ready in
  the queue until evictions free pages (the admission-resume path,
  tests/test_paged_engine.py).
- LAZY (``lazy=True``, the engine's ``--kv-lazy``): admission
  reserves only ``prompt + one dispatch span`` (the first decode
  window plus spec slack) and slots GROW their page tables at step
  boundaries (:meth:`grow_slot`, through ``reserve_with_epoch`` like
  every other page grab).  On real traffic outputs run short of
  budget, so full reservation leaves reserved-but-dead pages pinning
  concurrency below what the pool could hold; lazy reservation packs
  residents by what they have actually WRITTEN.  The price is a new
  failure mode — mid-decode pool exhaustion — which the engine owns:
  it preempts the resident with the most remaining budget through
  the PR 6/11 ``_evict_requeue`` path (token-identical resume) until
  the blocked growth fits, with a livelock-free re-admission policy
  (engine._ensure_lazy_growth).  The can-NEVER-fit shed at submit is
  unchanged (it is a capacity statement, not a reservation one), so
  a sole resident can always grow to its full budget — lazy mode is
  still deadlock-free.

Locking: page refcounts and the free list are mutated ONLY under
``_page_lock`` (machine-checked by the PAGE-REF rule in
analysis/rules.py — handler threads pin/unpin prefix pages while the
engine thread admits and releases).  Slot tables and the decode state
arrays stay engine-thread-only, like the fixed-lane manager's.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .slots import (alloc_decode_state, build_spec_step_body,
                    build_step_body, step_annotation)

__all__ = ["PagedSlotKVManager", "PageExhausted",
           "WirePayloadError", "pack_spilled", "unpack_spilled"]


class PageExhausted(RuntimeError):
    """Page reservation failed.  Engine admission is gated on
    ``can_admit`` so this is a defensive error, not a control path."""


class WirePayloadError(ValueError):
    """A serialized spill payload failed integrity verification
    (truncated body, checksum mismatch, malformed header).  Callers
    on the fetch path treat this as a typed MISS — fall back to
    re-prefill, never admit bytes that don't verify."""


# -- wire serialization (fleet prefix cache) -----------------------------
#
# A host-tier prefix entry is device-independent by construction
# (spill_pages gathered it to plain np arrays), which makes it
# REPLICA-independent too: the same buffers device_put cleanly into
# any replica's pool (rematerialize is byte-identical to materialize
# for the same content).  These helpers turn one spilled entry into a
# single self-describing byte string and back — pure host numpy, no
# device work, so they sit outside the TIER-XFER sanctioned set on
# purpose.  Layout: 4-byte big-endian header length, a JSON header
# (prompt tokens, leaf shapes/dtypes, logits shape/dtype, body
# crc32), then the raw C-order buffers concatenated (logits first).

_WIRE_VERSION = 1


def pack_spilled(toks: np.ndarray,
                 leaves: Sequence[Optional[np.ndarray]],
                 n_tokens: int, logits: np.ndarray) -> bytes:
    """Serialize one host-tier prefix entry for the wire."""
    import json
    import struct
    import zlib

    toks = np.ascontiguousarray(np.asarray(toks, np.int32))
    logits = np.ascontiguousarray(np.asarray(logits))
    parts = [logits.tobytes()]
    leaf_meta = []
    for h in leaves:
        if h is None:
            leaf_meta.append(None)
            continue
        h = np.ascontiguousarray(h)
        leaf_meta.append({"shape": list(h.shape),
                          "dtype": h.dtype.name})
        parts.append(h.tobytes())
    body = b"".join(parts)
    header = json.dumps({
        "v": _WIRE_VERSION,
        "n_tokens": int(n_tokens),
        "prompt": toks.tolist(),
        "logits": {"shape": list(logits.shape),
                   "dtype": logits.dtype.name},
        "leaves": leaf_meta,
        "crc32": zlib.crc32(body) & 0xFFFFFFFF,
    }).encode()
    return struct.pack(">I", len(header)) + header + body


def unpack_spilled(blob: bytes):
    """Parse + VERIFY a :func:`pack_spilled` byte string; returns
    ``(toks, leaves, n_tokens, logits)``.  Raises
    :class:`WirePayloadError` on any truncation, checksum mismatch,
    or malformed header — never a partially-decoded payload."""
    import json
    import struct
    import zlib

    if len(blob) < 4:
        raise WirePayloadError("payload shorter than its own "
                               "header-length field")
    (hlen,) = struct.unpack(">I", blob[:4])
    if len(blob) < 4 + hlen:
        raise WirePayloadError("payload truncated inside the header")
    try:
        header = json.loads(blob[4:4 + hlen].decode())
        version = header["v"]
        n_tokens = int(header["n_tokens"])
        prompt = header["prompt"]
        logits_meta = header["logits"]
        leaf_meta = header["leaves"]
        crc_want = int(header["crc32"])
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        raise WirePayloadError("malformed wire header")
    if version != _WIRE_VERSION:
        raise WirePayloadError(
            f"wire version {version!r} != {_WIRE_VERSION} "
            f"(mixed-version fleet; refetch or re-prefill)")
    body = blob[4 + hlen:]
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc_want:
        raise WirePayloadError("payload checksum mismatch")

    def _take(meta):
        nonlocal off
        a = np.empty(meta["shape"], np.dtype(meta["dtype"]))
        n = a.nbytes
        if off + n > len(body):
            raise WirePayloadError("payload truncated inside a "
                                   "buffer (header/body disagree)")
        a = np.frombuffer(body[off:off + n],
                          np.dtype(meta["dtype"])).reshape(
                              meta["shape"]).copy()
        off += n
        return a

    off = 0
    logits = _take(logits_meta)
    leaves: List[Optional[np.ndarray]] = []
    for m in leaf_meta:
        leaves.append(None if m is None else _take(m))
    if off != len(body):
        raise WirePayloadError(
            f"payload has {len(body) - off} trailing bytes past the "
            f"declared buffers")
    toks = np.asarray(prompt, np.int32)
    if toks.ndim != 2 or toks.shape[1] != n_tokens:
        raise WirePayloadError(
            "prompt/n_tokens disagree in the wire header")
    return toks, leaves, n_tokens, logits


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class PagedSlotKVManager:
    """Fixed pool of ``n_slots`` decode slots over a PAGED KV pool.

    Same engine-facing surface as :class:`slots.SlotKVManager`
    (acquire/release/insert/step/step_spec + the host decode-state
    arrays), plus the page accounting the engine's admission gate and
    the server's shared-prefix store ride on (``can_admit`` /
    ``pin`` / ``unpin`` / ``scatter_cache`` / ``materialize``).
    """

    paged = True

    def __init__(self, model, variables, n_slots: int, *,
                 page_tokens: int = 64, n_pages: Optional[int] = None,
                 max_position: int, decode_window: int = 8,
                 spec_k_cap: int = 4, lazy: bool = False,
                 draft_model=None, draft_variables=None,
                 sentinel=None, mesh=None):
        if mesh is not None and mesh.dp > 1:
            from ..parallel.mesh import MeshError

            raise MeshError(
                "paged KV does not support dp slot parallelism "
                "(pages migrate between slots, so the page axis has "
                "no stable dp decomposition); use tp/ep, or the "
                "fixed-lane manager for dp")
        if page_tokens < 8:
            raise ValueError(
                f"kv_page_tokens must be >= 8; got {page_tokens}")
        if max_position < 1:
            raise ValueError(
                f"paged KV needs the model's max_position; got "
                f"{max_position}")
        self.model = model
        self.variables = variables
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        self.sentinel = sentinel
        # Serving mesh (serving/meshed.py): page pools shard their
        # HEADS axis over tp; page tables/decode state stay host-side
        # and commit replicated through the programs' explicit
        # in_shardings.  Gather/scatter move pages within a head
        # shard — no cross-device math, so paged == fixed-lane
        # byte-identity holds per mesh shape.
        self.mesh = mesh
        self._pool_sh = None
        self._draft_pool_sh = None
        self.n_slots = int(n_slots)
        self.page_tokens = int(page_tokens)
        self.max_position = int(max_position)
        pt = self.page_tokens
        self.max_pages_slot = -(-self.max_position // pt)
        # Default pool = the fixed-lane footprint (n_slots full-width
        # lanes), so `kv_paged=True` alone changes layout, not budget.
        self.n_pages = int(n_pages) if n_pages is not None \
            else self.n_slots * self.max_pages_slot
        if self.n_pages < 1:
            raise ValueError(f"kv_pages must be >= 1; got {n_pages}")
        # Scratch page per slot (dead stepping of idle slots, and the
        # pad target beyond a short slot's real pages) + one TRASH
        # page (the redirected write target for content that must not
        # land on a shared page).  All garbage by definition, masked
        # by absolute position before any read could admit them.
        self.scratch0 = self.n_pages
        self.trash = self.n_pages + self.n_slots
        self.total_pages = self.n_pages + self.n_slots + 1
        # Dirty-window bound: the widest position span one step
        # dispatch can write (a spec round writes K+1 wide per round).
        self._span_cap = max(1, int(decode_window)) \
            * max(1, int(spec_k_cap)) + 1
        # Lazy admission/growth span: the widest span THIS pool's
        # dispatches can actually write — spec rounds only exist
        # when a draft model does, so a plain pool's "first decode
        # window" is decode_window tokens, not the spec worst case
        # (which would front-load most of a short budget and erase
        # the lazy win).
        self._grow_span = max(1, int(decode_window)) \
            * (max(1, int(spec_k_cap))
               if draft_model is not None else 1) + 1
        self._n_dirty_cap = (self._span_cap - 1 + pt - 1) // pt + 1
        # Table width covers the largest possible reservation plus
        # the dirty-window margin (so d0 + n_dirty always lands
        # inside the table and no clamp is ever needed).
        need_cap = (self.max_position + int(spec_k_cap)
                    + pt - 1) // pt
        self.table_width = _pow2ceil(need_cap + self._n_dirty_cap)

        # -- page accounting (under _page_lock) ------------------------
        self._page_lock = threading.Lock()
        with self._page_lock:
            self.refcounts = np.zeros((self.total_pages,), np.int64)
            self.refcounts[self.n_pages:] = 1  # scratch/trash pinned
            self._free_pages: List[int] = list(range(self.n_pages))
            # Pool GENERATION: bumped by the crash-recovery reset().
            # Page ids are only meaningful within one epoch — pin()
            # returns the epoch the pins were taken under, and
            # epoch-tagged unpins/shares from a dead generation are
            # dropped by reference instead of corrupting the fresh
            # accounting.
            self.epoch = 0

        # -- slot state (engine thread only) ---------------------------
        self._free = list(range(self.n_slots))
        self.page_tables = np.empty((self.n_slots, self.table_width),
                                    np.int32)
        for s in range(self.n_slots):
            self.page_tables[s, :] = self.scratch0 + s
        self._slot_pages: List[Optional[Tuple[List[int], int]]] = \
            [None] * self.n_slots           # (page ids, n shared)
        self._slot_need = np.zeros((self.n_slots,), np.int32)
        # LAZY reservation mode (module docstring): admission
        # reserves one dispatch span past the prompt; the engine
        # grows tables at step boundaries (grow_slot) up to each
        # slot's full budget (_slot_budget, in pages).  The growth
        # counters are monotonic totals (survive reset(), like every
        # other counter behind /metrics).
        self.lazy = bool(lazy)
        self._slot_budget = np.zeros((self.n_slots,), np.int32)
        self.lazy_growths_total = 0
        self.lazy_pages_grown_total = 0

        # -- device pools ---------------------------------------------
        self._pool: Optional[List[Any]] = None       # per paged leaf
        self._meta: Optional[List[Dict[str, Any]]] = None
        self._treedef = None
        self._draft_pool: Optional[List[Any]] = None
        self._draft_meta: Optional[List[Dict[str, Any]]] = None
        self._draft_treedef = None
        self._step_fns: Dict[Tuple, Any] = {}
        self._insert_fns: Dict[Tuple, Any] = {}
        self._gather_fns: Dict[int, Any] = {}
        # First-touch pool shaping is double-checked under this lock:
        # two concurrent handoffs racing a FRESH replica's unshaped
        # pool (ensure_shaped from two wire admissions) must not both
        # allocate — the loser's pool would replace a pool the winner
        # already wrote pages into, silently dropping its KV.
        self._shape_lock = threading.Lock()

        # -- per-slot decode state (identical to SlotKVManager;
        # shared helper, also called by crash-recovery reset()) -----
        alloc_decode_state(self)
        self.last_step_device_s = 0.0

    # -- page accounting ------------------------------------------------

    def pages_needed(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_tokens))

    def admit_tokens(self, cur_tokens: int, total_tokens: int) -> int:
        """Tokens a new admission must have pages for UP FRONT: the
        full reservation (default — deadlock-free by construction),
        or — lazy — just the request's current length plus one
        dispatch span (the first decode window incl. spec slack),
        the rest growing at step boundaries (grow_slot)."""
        if not self.lazy:
            return int(total_tokens)
        return min(int(total_tokens),
                   int(cur_tokens) + self._grow_span)

    @property
    def capacity_tokens(self) -> int:
        return self.n_pages * self.page_tokens

    def free_page_count(self) -> int:
        with self._page_lock:
            return len(self._free_pages)

    def can_admit(self, tokens: int, shared_pages: int = 0) -> bool:
        """Enough free pages for a ``tokens``-long reservation, of
        which ``shared_pages`` leading pages are already mapped
        (pinned prefix pages)?"""
        need = self.pages_needed(tokens) - int(shared_pages)
        with self._page_lock:
            return len(self._free_pages) >= need

    def pin(self, ids: Sequence[int]) -> int:
        """Take one reference on each page (prefix-cache lookups pin
        an entry's pages so eviction/reuse can't free them while a
        request maps or materializes them).  Returns the pool EPOCH
        the pins were taken under — callers that hold pins across
        their own lock scope (the prefix-hit handler path) carry it
        so a crash-recovery pool rebuild in between invalidates the
        pins instead of corrupting the fresh refcounts."""
        with self._page_lock:
            for i in ids:
                if self.refcounts[i] < 1:
                    raise ValueError(
                        f"pin of a free page {i} (stale page id — "
                        f"the entry holding it was already freed)")
                self.refcounts[i] += 1
            return self.epoch

    def unpin(self, ids: Sequence[int],
              epoch: Optional[int] = None) -> None:
        """Drop one reference per page; pages hitting zero return to
        the free list.  ``epoch`` (when the caller carried one from
        ``pin``) guards the crash-recovery race: pins from a dead
        pool generation are dropped BY REFERENCE — the ids mean
        nothing in the rebuilt accounting."""
        with self._page_lock:
            if epoch is not None and epoch != self.epoch:
                return
            for i in ids:
                if self.refcounts[i] < 1:
                    raise ValueError(f"unpin of a free page {i}")
                self.refcounts[i] -= 1
                if self.refcounts[i] == 0:
                    self._free_pages.append(i)

    def try_reserve(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` free pages (refcount 0 -> 1), or None if fewer
        are free."""
        return self.reserve_with_epoch(n)[0]

    def reserve_with_epoch(self, n: int
                           ) -> Tuple[Optional[List[int]], int]:
        """``try_reserve`` plus the pool epoch the reservation was
        made under, read atomically in one lock hold — for callers
        (the prefix store) that carry the ids across their own lock
        scopes and must recognize a crash-recovery pool rebuild in
        between."""
        with self._page_lock:
            if n <= 0:
                return [], self.epoch
            if len(self._free_pages) < n:
                return None, self.epoch
            ids = [self._free_pages.pop() for _ in range(n)]
            for i in ids:
                self.refcounts[i] = 1
            return ids, self.epoch

    def page_stats(self) -> Dict[str, int]:
        with self._page_lock:
            free = len(self._free_pages)
            shared = int(np.sum(self.refcounts[:self.n_pages] > 1))
        resident = int(sum(len(p[0]) for p in self._slot_pages
                           if p is not None))
        return {
            "kv_pages": self.n_pages,
            "kv_page_tokens": self.page_tokens,
            "kv_pages_free": free,
            "kv_pages_resident": resident,
            "kv_pages_shared": shared,
            "kv_lazy": self.lazy,
            "kv_pages_lazy_growths_total": self.lazy_growths_total,
            "kv_pages_lazy_grown_total": self.lazy_pages_grown_total,
        }

    def slot_page_counts(self) -> Dict[int, int]:
        """Mapped pool pages per RESIDENT slot (``/debug/state``'s
        per-slot table-size column) — the accounting API's answer so
        introspection never reads pool internals directly
        (PAGE-REF)."""
        out: Dict[int, int] = {}
        for slot, held in enumerate(self._slot_pages):
            if held is not None:
                out[slot] = len(held[0])
        return out

    # -- slot accounting ------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> int:
        return self.n_slots - len(self._free)

    def acquire(self) -> Optional[int]:
        return self._free.pop(0) if self._free else None

    def reset(self) -> None:
        """Crash-recovery pool rebuild (recovery.EngineSupervisor):
        every page reference — resident tables, prefix-store pins,
        shared refcounts — is dropped WHOLESALE and the page pool
        returns to all-free, while the compiled step/insert/gather
        programs are KEPT (a supervised restart must add zero
        steady-state recompiles).  Callers own the invalidation
        story: stale page ids must never be unpinned into the fresh
        accounting (the engine clears stream pins by reference; the
        server's recovery hook flushes the prefix store whose
        payloads these pages backed)."""
        with self._page_lock:
            self.refcounts[:] = 0
            self.refcounts[self.n_pages:] = 1  # scratch/trash pinned
            self._free_pages = list(range(self.n_pages))
            self.epoch += 1     # prior-generation page ids are dead
        self._free = list(range(self.n_slots))
        for s in range(self.n_slots):
            self.page_tables[s, :] = self.scratch0 + s
        self._slot_pages = [None] * self.n_slots
        self._slot_need[:] = 0
        self._slot_budget[:] = 0
        self._pool = None
        self._draft_pool = None
        alloc_decode_state(self)

    def release(self, slot: int) -> None:
        """Evict: park the slot (same contract as the fixed-lane
        release — see SlotKVManager.release) AND return its pages:
        one reference dropped per mapped page, so privately-owned
        pages free immediately while shared prefix pages live on
        under the entries/slots still referencing them."""
        if slot in self._free:
            raise ValueError(f"slot {slot} already free")
        self._free.append(slot)
        self._free.sort()
        held = self._slot_pages[slot]
        if held is not None:
            self._slot_pages[slot] = None
            self.unpin(held[0])
        self.page_tables[slot, :] = self.scratch0 + slot
        self._slot_need[slot] = 0
        self._slot_budget[slot] = 0
        self.tokens[slot] = 0
        self.positions[slot] = 0
        self.keys[slot] = 0
        self.next_index[slot] = 0
        self.temps[slot] = 0.0
        self.top_ks[slot] = 0
        self.top_ps[slot] = 0.0
        self.spec_ks[slot] = 0

    # -- leaf classification / pools ------------------------------------

    def _classify(self, template):
        """Flatten a template cache and classify each leaf: PAGED
        (one axis == max_position — the position axis that splits
        into pages) or INDEX (``cache_index`` leaves, rebuilt from
        the slot position at gather time).  Anything else (e.g. a
        ring cache's position table) is unsupported — the server
        gates paged mode to plain/int8 caches."""
        import jax

        leaves_p, treedef = jax.tree_util.tree_flatten_with_path(
            template)
        metas = []
        for path, leaf in leaves_p:
            key = jax.tree_util.keystr(path)
            if key.endswith("cache_index']"):
                metas.append({"kind": "index", "shape": leaf.shape,
                              "dtype": leaf.dtype})
                continue
            # The standard cache leaves (kv_cache.append_kv_cache)
            # are [..., B, positions, heads, feat]: position is the
            # THIRD-FROM-LAST axis, whatever leading layer-stack axes
            # scan_stack added.  Prefer that known layout — a head
            # count or head dim that coincidentally equals
            # max_position must not confuse the classifier — and fall
            # back to a unique max_position axis for unknown names.
            named = any(key.endswith(f"{n}']") for n in (
                "cached_key", "cached_value", "cached_key_scale",
                "cached_value_scale"))
            if named and leaf.ndim >= 3 \
                    and leaf.shape[leaf.ndim - 3] == self.max_position:
                metas.append({"kind": "paged",
                              "pos_axis": leaf.ndim - 3,
                              # Pool heads axis for mesh sharding:
                              # the position axis splits into
                              # (pages, page_tokens), pushing heads
                              # from leaf ndim-2 to pool ndim-1... +1
                              # overall = pos_axis + 2.
                              "heads_axis": leaf.ndim - 3 + 2,
                              "shape": leaf.shape,
                              "dtype": leaf.dtype})
                continue
            axes = [i for i, d in enumerate(leaf.shape)
                    if d == self.max_position]
            if len(axes) != 1:
                raise ValueError(
                    f"paged KV cannot page cache leaf {key} of shape "
                    f"{leaf.shape}: expected the [..., B, positions, "
                    f"heads, feat] layout or exactly one axis of "
                    f"max_position ({self.max_position}); ring "
                    f"caches and exotic layouts need the fixed-lane "
                    f"manager")
            metas.append({"kind": "paged", "pos_axis": axes[0],
                          "shape": leaf.shape, "dtype": leaf.dtype})
        return metas, treedef

    def _exact(self):
        """Serving-exact trace context (no-op unmeshed)."""
        return self.mesh.exact() if self.mesh is not None \
            else contextlib.nullcontext()

    def _alloc_pool(self, metas):
        """Zero-init pool leaves (None for index leaves); meshed
        pools commit each paged leaf to its heads-over-tp
        NamedSharding at birth.  Returns (pool, shardings)."""
        import jax
        import jax.numpy as jnp

        from ..models.kv_cache import paged_pool_shape

        pool, shardings = [], []
        for m in metas:
            if m["kind"] != "paged":
                pool.append(None)
                shardings.append(None)
                continue
            leaf = jnp.zeros(paged_pool_shape(
                m["shape"], m["pos_axis"], self.total_pages,
                self.page_tokens), m["dtype"])
            if self.mesh is not None:
                sh = self.mesh.pool_leaf_sharding(m, leaf)
                leaf = jax.device_put(leaf, sh)
                shardings.append(sh)
            else:
                shardings.append(None)
            pool.append(leaf)
        return pool, shardings

    def _ensure_pool(self, template_cache) -> None:
        if self._pool is not None:
            return
        with self._shape_lock:
            if self._pool is not None:      # lost the race: done
                return
            meta, treedef = self._classify(template_cache)
            pool, pool_sh = self._alloc_pool(meta)
            # Publish LAST, fully formed: a concurrent ``shaped``
            # reader must never observe meta without its pool.
            self._meta, self._treedef = meta, treedef
            self._pool_sh = pool_sh
            self._pool = pool

    @property
    def shaped(self) -> bool:
        """Whether the main pool's leaf layout is known yet (shaped
        by the first page write, or by :meth:`ensure_shaped`)."""
        return self._meta is not None

    def ensure_shaped(self, template_cache) -> None:
        """Shape the main pool from a template WITHOUT a page write.
        Classification reads only tree paths, shapes and dtypes, so
        an ABSTRACT template (``jax.eval_shape`` pytree of
        ``ShapeDtypeStruct`` leaves) works — no model compute, no
        template allocation.  This is the cold-pool escape hatch for
        the fleet prefix tier: a wire-fetched or handed-off host
        entry can arrive BEFORE this replica's first prefill (a
        freshly restarted drain successor), and its rematerialize
        must not depend on prior traffic.  Safe under concurrent
        first-touch (two handoffs racing a fresh replica's unshaped
        pool): shaping is double-checked under an internal lock, so
        exactly one caller allocates and the rest observe the
        finished pool."""
        self._ensure_pool(template_cache)

    def _ensure_draft_pool(self, template_cache) -> None:
        if self._draft_pool is not None:
            return
        with self._shape_lock:
            if self._draft_pool is not None:
                return
            meta, treedef = self._classify(template_cache)
            pool, pool_sh = self._alloc_pool(meta)
            self._draft_meta, self._draft_treedef = meta, treedef
            self._draft_pool_sh = pool_sh
            self._draft_pool = pool

    def _pad_class(self, n_pages: int) -> int:
        return min(self.table_width, _pow2ceil(max(1, n_pages)))

    # -- gather / scatter program pieces --------------------------------

    def _gather_tree(self, pool, metas, treedef, tables, positions):
        """Stacked [S, ...] cache pytree from the pool: paged leaves
        gather through the page tables into position-contiguous
        views; index leaves rebuild from the slot positions."""
        import jax
        import jax.numpy as jnp

        leaves = []
        for m, p in zip(metas, pool):
            if m["kind"] == "index":
                leaves.append(jax.vmap(
                    lambda pos, m=m: jnp.full(m["shape"], pos,
                                              m["dtype"]))(positions))
                continue
            a = m["pos_axis"]
            v = jnp.take(p, tables, axis=a)
            # lead + (S, P, pt) + rest -> (S,) + lead + (P*pt,) + rest
            v = jnp.moveaxis(v, a, 0)
            shape = v.shape
            leaves.append(v.reshape(
                (shape[0],) + shape[1:a + 1]
                + (shape[a + 1] * shape[a + 2],) + shape[a + 3:]))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _scatter_dirty(self, pool, metas, stacked, tables, d0,
                       n_dirty: int):
        """Write each slot's dirty page window ([d0, d0 + n_dirty)
        local pages — everything this dispatch could have written)
        back to the pool.  Dirty pages are private by construction
        (decode writes start at the prompt end, past any shared
        page), so targets never collide except on scratch/trash
        garbage."""
        import jax
        import jax.numpy as jnp

        from ..models.kv_cache import scatter_pages

        leaves, _ = jax.tree_util.tree_flatten(stacked)
        pt = self.page_tokens
        idx = jax.vmap(lambda t, d: jax.lax.dynamic_slice(
            t, (d,), (n_dirty,)))(tables, d0)       # [S, n_dirty]
        flat_idx = idx.reshape(-1)
        out = []
        for m, p, leaf in zip(metas, pool, leaves):
            if m["kind"] == "index":
                out.append(None)
                continue
            a = m["pos_axis"]

            def slice_one(v, d, a=a):
                return jax.lax.dynamic_slice_in_dim(
                    v, d * pt, n_dirty * pt, axis=a)

            dirty = jax.vmap(slice_one)(leaf, d0)
            s = dirty.shape          # (S,) + lead + (n_dirty*pt,) + rest
            dirty = dirty.reshape(s[:a + 1] + (n_dirty, pt)
                                  + s[a + 2:])
            dirty = jnp.moveaxis(dirty, 0, a)
            s = dirty.shape          # lead + (S, n_dirty, pt) + rest
            dirty = dirty.reshape(s[:a] + (s[a] * s[a + 1],)
                                  + s[a + 2:])
            out.append(scatter_pages(p, dirty, flat_idx, a))
        return out

    def _scatter_cache_leaves(self, pool, metas, cache, targets,
                              P: int):
        """Scatter a contiguous B=1 cache's first ``P * page_tokens``
        positions into pool pages ``targets`` [P] (shared entries are
        pre-munged to the trash page by the host caller)."""
        import jax
        import jax.numpy as jnp

        from ..models.kv_cache import scatter_pages

        leaves, _ = jax.tree_util.tree_flatten(cache)
        pt = self.page_tokens
        width = P * pt
        out = []
        for m, p, leaf in zip(metas, pool, leaves):
            if m["kind"] == "index":
                out.append(None)
                continue
            a = m["pos_axis"]
            have = leaf.shape[a]
            if have < width:
                pad = [(0, 0)] * leaf.ndim
                pad[a] = (0, width - have)
                leaf = jnp.pad(leaf, pad)
            elif have > width:
                leaf = jax.lax.slice_in_dim(leaf, 0, width, axis=a)
            s = leaf.shape
            pages = leaf.reshape(s[:a] + (P, pt) + s[a + 1:])
            out.append(scatter_pages(p, pages, targets, a))
        return out

    # -- insert / prefix-store scatter ----------------------------------

    def _insert_fn(self, P: int, draft: bool):
        import jax

        key = (P, draft)
        fn = self._insert_fns.get(key)
        if fn is None:
            if self.sentinel is not None:
                self.sentinel.miss("page_insert", key)
            metas = self._draft_meta if draft else self._meta

            def ins(pool, cache, targets):
                return self._scatter_cache_leaves(pool, metas, cache,
                                                  targets, P)

            if self.mesh is not None:
                sh = self._draft_pool_sh if draft else self._pool_sh
                fn = jax.jit(ins, in_shardings=(sh, None, None),
                             out_shardings=sh)
            else:
                fn = jax.jit(ins)
            self._insert_fns[key] = fn
        elif self.sentinel is not None:
            self.sentinel.hit("page_insert", key)
        return fn

    def _write_targets(self, ids: List[int], n_shared: int,
                       P: int) -> np.ndarray:
        """Scatter targets for a cache write over pages ``ids``:
        already-populated SHARED pages redirect to the trash page
        (their content is identical by the prefix contract — never
        rewrite a page with refcount > 1), and pad entries past the
        real pages also land in trash."""
        tg = np.full((P,), self.trash, np.int32)
        if len(ids) > n_shared:
            tg[n_shared:len(ids)] = np.asarray(ids[n_shared:],
                                               np.int32)
        return tg

    def scatter_cache(self, cache, ids: List[int],
                      n_shared: int = 0, *, draft: bool = False
                      ) -> None:
        """Write a contiguous B=1 cache into pages ``ids`` (first
        ``n_shared`` already hold the same content and are skipped
        via trash redirect).  Device work — callers hold the device
        lock."""
        if draft:
            self._ensure_draft_pool(cache)
        else:
            self._ensure_pool(cache)
        P = self._pad_class(len(ids))
        tg = self._write_targets(ids, n_shared, P)
        import jax.numpy as jnp

        with self._exact():
            if draft:
                self._draft_pool = self._insert_fn(P, True)(
                    self._draft_pool, cache, jnp.asarray(tg))
            else:
                self._pool = self._insert_fn(P, False)(
                    self._pool, cache, jnp.asarray(tg))

    def insert(self, slot: int, cache, first_token: int,
               position: int, *, base_key=None, next_index: int = 1,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, draft_cache=None,
               spec_k: int = 0, total_tokens: Optional[int] = None,
               shared_pages: Sequence[int] = ()) -> None:
        """Admit a prefilled request: reserve its page budget, build
        its table, scatter the prefilled cache into its PRIVATE pages
        (shared prefix pages are mapped, not rewritten), and arm the
        slot's decode state (identical to the fixed-lane insert).

        ``total_tokens`` is the request's full KV budget (prompt +
        new tokens + speculative slack).  FULL mode reserves all of
        it — the reservation that makes mid-decode page exhaustion
        impossible; LAZY mode reserves ``admit_tokens`` (current
        length + one dispatch span) and records the full budget as
        the growth cap (``_slot_budget``).  ``shared_pages`` are
        pinned prefix-page ids whose references this call TAKES
        OWNERSHIP of (released with the rest at slot release)."""
        if total_tokens is None:
            total_tokens = self.max_position
        n_total = self.pages_needed(total_tokens)
        n_need = self.pages_needed(self.admit_tokens(
            position + 1, total_tokens)) if self.lazy else n_total
        shared = list(shared_pages)
        if len(shared) > n_need:       # defensive: over-wide prefix
            self.unpin(shared[n_need:])
            shared = shared[:n_need]
        priv = self.try_reserve(n_need - len(shared))
        if priv is None:
            self.unpin(shared)
            raise PageExhausted(
                f"admission needs {n_need - len(shared)} free pages "
                f"(have {self.free_page_count()}): engine admission "
                f"gate out of sync")
        ids = shared + priv
        try:
            self.scatter_cache(cache, ids, n_shared=len(shared))
            if draft_cache is not None:
                # Mirrored page ids: the draft pool is allocated with
                # the same page geometry, so one table serves both.
                self.scatter_cache(draft_cache, ids,
                                   n_shared=len(shared), draft=True)
        except BaseException:
            self.unpin(ids)
            raise
        self.page_tables[slot, :] = self.scratch0 + slot
        self.page_tables[slot, :len(ids)] = np.asarray(ids, np.int32)
        self._slot_pages[slot] = (ids, len(shared))
        self._slot_need[slot] = n_need
        self._slot_budget[slot] = n_total
        self.tokens[slot] = first_token
        self.positions[slot] = position
        if base_key is not None:
            self.keys[slot] = np.asarray(base_key, np.uint32)
        else:
            self.keys[slot] = 0
        self.next_index[slot] = next_index
        self.temps[slot] = temperature
        self.top_ks[slot] = top_k
        self.top_ps[slot] = top_p
        self.spec_ks[slot] = spec_k

    # -- lazy growth (engine thread, step boundaries) --------------------

    def grow_need(self, slot: int, tokens: int) -> int:
        """Pages a ``grow_slot(slot, tokens)`` would still have to
        reserve (0 = the table already covers it) — what the
        engine's exhaustion path feeds the page-reclaim hook before
        preempting anyone."""
        held = self._slot_pages[slot]
        if held is None:
            raise ValueError(f"grow_need of a free slot {slot}")
        want = min(self.pages_needed(tokens),
                   int(self._slot_budget[slot]))
        return max(0, want - len(held[0]))

    def grow_slot(self, slot: int, tokens: int) -> Optional[int]:
        """LAZY growth at a step boundary: extend ``slot``'s table so
        it holds pages for ``tokens`` positions, capped at the slot's
        full budget.  Returns the number of pages grown (0 = already
        wide enough), or None on POOL EXHAUSTION — the engine's
        preempt-on-exhaustion path owns what happens next.  Engine
        thread only (it mutates the slot table); the reservation
        itself goes through ``reserve_with_epoch`` — one
        ``_page_lock`` hold — like every other page grab, so handler
        threads (prefix pins/stores) interleave safely.

        Freshly grown pages hold garbage until the decode step writes
        them — masked by absolute position before any query could
        admit them, the same argument every reserved-but-unwritten
        page already rides."""
        held = self._slot_pages[slot]
        if held is None:
            raise ValueError(f"grow of a free slot {slot}")
        ids, _n_shared = held
        want = min(self.pages_needed(tokens),
                   int(self._slot_budget[slot]))
        delta = want - len(ids)
        if delta <= 0:
            return 0
        fresh, _epoch = self.reserve_with_epoch(delta)
        if fresh is None:
            return None
        start = len(ids)
        ids.extend(fresh)
        self.page_tables[slot, start:start + delta] = \
            np.asarray(fresh, np.int32)
        self._slot_need[slot] = len(ids)
        self.lazy_growths_total += 1
        self.lazy_pages_grown_total += delta
        return delta

    # -- prefix materialization -----------------------------------------

    def materialize(self, ids: Sequence[int], n_tokens: int):
        """Gather stored prefix pages into a CONTIGUOUS B=1 cache of
        the model's full creation width (``max_position``) — exactly
        the shape the prefill/extend programs expect, so a prefix hit
        reuses every existing compiled program.  Device work — caller
        holds the device lock and a pin on every page in ``ids``."""
        import jax
        import jax.numpy as jnp

        if self._pool is None:
            raise RuntimeError("materialize() before any page write")
        P = self._pad_class(len(ids))
        fn = self._gather_fns.get(P)
        if fn is None:
            if self.sentinel is not None:
                self.sentinel.miss("page_gather", P)
            metas, treedef = self._meta, self._treedef
            pt, width = self.page_tokens, self.max_position

            def gather_cc(pool, table, pos):
                from ..models.kv_cache import gather_pages

                leaves = []
                for m, p in zip(metas, pool):
                    if m["kind"] == "index":
                        leaves.append(jnp.full(m["shape"], pos,
                                               m["dtype"]))
                        continue
                    a = m["pos_axis"]
                    v = gather_pages(p, table, a)
                    have = v.shape[a]
                    if have < width:
                        padw = [(0, 0)] * v.ndim
                        padw[a] = (0, width - have)
                        v = jnp.pad(v, padw)
                    elif have > width:
                        v = jax.lax.slice_in_dim(v, 0, width, axis=a)
                    leaves.append(v)
                return jax.tree_util.tree_unflatten(treedef, leaves)

            if self.mesh is not None:
                # Materialized prefix caches feed the ordinary
                # prefill/extend programs — gather them back to a
                # REPLICATED contiguous cache.
                fn = jax.jit(gather_cc,
                             in_shardings=(self._pool_sh, None, None),
                             out_shardings=self.mesh.replicated)
            else:
                fn = jax.jit(gather_cc)
            self._gather_fns[P] = fn
        elif self.sentinel is not None:
            self.sentinel.hit("page_gather", P)
        table = np.full((P,), self.trash, np.int32)
        table[:len(ids)] = np.asarray(ids, np.int32)
        with self._exact():
            return fn(self._pool, jnp.asarray(table),
                      jnp.asarray(n_tokens, np.int32))

    # -- host-RAM tier (prefix-store spill / re-materialize) -------------
    #
    # The SANCTIONED device<->host transfer helpers for page-pool
    # payloads (the TIER-XFER rule, analysis/rules.py): a prefix
    # entry evicted from the device pool under page pressure spills
    # its payload to host buffers here instead of dropping it, and a
    # later hit re-materializes via ``device_put`` + the existing
    # contiguous-cache plumbing.  Both are device work — callers
    # hold the device lock — and both are OFF the decode step path
    # (spills ride page-pressure reclaim, re-materialization rides a
    # prefix hit's admission, never a step dispatch).

    def spill_pages(self, ids: Sequence[int], n_tokens: int
                    ) -> List[Optional[np.ndarray]]:
        """Gather stored prefix pages into HOST buffers: one np array
        per paged cache leaf (None for index leaves), trimmed to the
        entry's page-aligned span so host bytes track content, not
        ``max_position`` headroom.  Caller holds the device lock and
        a pin on every page in ``ids``."""
        import jax

        cache = self.materialize(ids, n_tokens)
        leaves, _ = jax.tree_util.tree_flatten(cache)
        width = len(ids) * self.page_tokens
        host: List[Optional[np.ndarray]] = []
        for m, leaf in zip(self._meta, leaves):
            if m["kind"] == "index":
                host.append(None)
                continue
            a = m["pos_axis"]
            v = jax.lax.slice_in_dim(
                leaf, 0, min(width, leaf.shape[a]), axis=a)
            host.append(np.asarray(jax.device_get(v)))
        return host

    def rematerialize(self, host_leaves: Sequence[Optional[np.ndarray]],
                      n_tokens: int):
        """Host-tier hit: ``device_put`` the spilled leaves back into
        a CONTIGUOUS B=1 cache of the model's full creation width —
        byte-identical to what :meth:`materialize` returns for the
        same content, so every downstream consumer (extend programs,
        slot insert, page promotion via ``scatter_cache``) is reused
        unchanged.  Caller holds the device lock."""
        import jax
        import jax.numpy as jnp

        if self._meta is None:
            raise RuntimeError("rematerialize() before any page "
                               "write shaped the pool")
        width = self.max_position
        leaves = []
        for m, h in zip(self._meta, host_leaves):
            if m["kind"] == "index":
                leaves.append(jnp.full(m["shape"],
                                       np.int32(n_tokens), m["dtype"]))
                continue
            a = m["pos_axis"]
            have = h.shape[a]
            if have < width:
                pad = [(0, 0)] * h.ndim
                pad[a] = (0, width - have)
                h = np.pad(h, pad)
            elif have > width:
                h = np.take(h, range(width), axis=a)
            h = h.astype(m["dtype"], copy=False)
            # COMMITTED placement both ways (SHARD-LEAK): replicated
            # over the serving mesh, or pinned to the default device.
            sh = self.mesh.replicated if self.mesh is not None \
                else jax.devices()[0]
            leaves.append(jax.device_put(h, sh))
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- decode steps ----------------------------------------------------

    def _resident_pad(self) -> int:
        """Pad class for this dispatch's page tables: pow2 of the
        widest resident reservation, so the compiled program set
        stays bounded and steady-state quiet — and so the gathered
        view (the dispatch's attention width) tracks the RESIDENT
        MIX, not the worst case.  The dirty-window slice clamps its
        start instead of padding the class (see step's d0)."""
        need = int(self._slot_need.max()) if self.n_slots else 1
        return self._pad_class(max(need, self._n_dirty_cap))

    def _n_dirty(self, span: int) -> int:
        pt = self.page_tokens
        return (span - 1 + pt - 1) // pt + 1

    def _dirty_start(self, P: int, n_dirty: int) -> np.ndarray:
        """Per-slot first dirty page for this dispatch, CLAMPED so
        the static-width dirty slice always fits the table.  The
        clamp can shift a window over earlier pages the slot already
        holds — harmless: the gathered view carries their current
        content untouched, so the write-back is byte-identical (for
        the rare boundary case where the earlier page is a SHARED
        prefix page, identical bytes under the serialized device lock
        are benign — content equality is the invariant, and no reader
        can observe a difference)."""
        d0 = self.positions // self.page_tokens
        return np.clip(d0, 0, max(0, P - n_dirty)).astype(np.int32)

    def _build_step(self, window: int, sampled: bool, P: int):
        import jax

        body = build_step_body(self.model, self.variables, window,
                               sampled)
        metas, treedef = self._meta, self._treedef
        n_dirty = self._n_dirty(window)

        def step(pool, tables, d0, toks, positions, *extra):
            stacked = self._gather_tree(pool, metas, treedef,
                                        tables, positions)
            outs, stacked = body(stacked, toks, positions, *extra)
            pool = self._scatter_dirty(pool, metas, stacked, tables,
                                       d0, n_dirty)
            return outs, pool

        if self.mesh is None:
            return jax.jit(step)
        rep = self.mesh.replicated
        n_extra = 5 if sampled else 0
        in_sh = (self._pool_sh, rep, rep, rep, rep) \
            + (rep,) * n_extra
        return jax.jit(step, in_shardings=in_sh,
                       out_shardings=(rep, self._pool_sh))

    def step(self, window: int = 1, sampled: bool = False
             ) -> np.ndarray:
        """``window`` fused decode steps across the whole pool — the
        paged twin of SlotKVManager.step: gather views, run the SAME
        decode body, scatter dirty pages.  One compiled program per
        (window, sampled, pages-per-slot pad class)."""
        import jax
        import jax.numpy as jnp

        if self._pool is None:
            raise RuntimeError("step() before any insert()")
        P = self._resident_pad()
        key = (window, sampled, P)
        fn = self._step_fns.get(key)
        if fn is None:
            if self.sentinel is not None:
                self.sentinel.miss("slot_step", key)
            fn = self._step_fns[key] = self._build_step(
                window, sampled, P)
        elif self.sentinel is not None:
            self.sentinel.hit("slot_step", key)
        tables = jnp.asarray(self.page_tables[:, :P])
        d0 = jnp.asarray(self._dirty_start(P, self._n_dirty(window)))
        t0 = time.perf_counter()
        with self._exact(), step_annotation():
            if sampled:
                outs, self._pool = fn(
                    self._pool, tables, d0, jnp.asarray(self.tokens),
                    jnp.asarray(self.positions),
                    jnp.asarray(self.keys),
                    jnp.asarray(self.next_index),
                    jnp.asarray(self.temps), jnp.asarray(self.top_ks),
                    jnp.asarray(self.top_ps))
            else:
                outs, self._pool = fn(
                    self._pool, tables, d0, jnp.asarray(self.tokens),
                    jnp.asarray(self.positions))
            # Sync inside the marker so it spans the device
            # execution, not just the async enqueue (see slots.py).
            outs = np.asarray(jax.device_get(outs))
        self.last_step_device_s = time.perf_counter() - t0
        self.tokens = outs[-1].copy()
        self.positions = self.positions + window
        self.next_index = self.next_index + window
        if self._free:
            idle = np.asarray(self._free, np.int32)
            self.tokens[idle] = 0
            self.positions[idle] = 0
            self.next_index[idle] = 0
        return outs

    def _build_spec_step(self, window: int, K: int, P: int):
        import jax

        body = build_spec_step_body(
            self.model, self.variables, self.draft_model,
            self.draft_variables, window, K)
        metas, treedef = self._meta, self._treedef
        d_metas, d_treedef = self._draft_meta, self._draft_treedef
        n_dirty = self._n_dirty(window * K + 1)

        def step(t_pool, d_pool, tables, d0, toks, positions, idxs,
                 keys, temps, tks, tps, sks):
            t_stacked = self._gather_tree(t_pool, metas, treedef,
                                          tables, positions)
            d_stacked = self._gather_tree(d_pool, d_metas, d_treedef,
                                          tables, positions)
            outs, cs, ms, t_stacked, d_stacked = body(
                t_stacked, d_stacked, toks, positions, idxs, keys,
                temps, tks, tps, sks)
            t_pool = self._scatter_dirty(t_pool, metas, t_stacked,
                                         tables, d0, n_dirty)
            d_pool = self._scatter_dirty(d_pool, d_metas, d_stacked,
                                         tables, d0, n_dirty)
            return outs, cs, ms, t_pool, d_pool

        if self.mesh is None:
            return jax.jit(step)
        rep = self.mesh.replicated
        in_sh = (self._pool_sh, self._draft_pool_sh) + (rep,) * 10
        return jax.jit(step, in_shardings=in_sh,
                       out_shardings=(rep, rep, rep, self._pool_sh,
                                      self._draft_pool_sh))

    def step_spec(self, window: int, K: int):
        """``window`` fused SPECULATIVE rounds — the paged twin of
        SlotKVManager.step_spec.  The in-program rollback stays a
        pure ``cache_index`` rewind on the gathered view: pages are
        reserved to budget, so rejection never touches the page
        accounting (no truncation, no refcount traffic — the
        full-reservation dividend)."""
        import jax
        import jax.numpy as jnp

        if self._pool is None or self._draft_pool is None:
            raise RuntimeError("step_spec() before a speculative "
                               "insert()")
        P = self._resident_pad()
        key = (window, "spec", K, P)
        fn = self._step_fns.get(key)
        if fn is None:
            if self.sentinel is not None:
                self.sentinel.miss("slot_step", key)
            fn = self._step_fns[key] = self._build_spec_step(
                window, K, P)
        elif self.sentinel is not None:
            self.sentinel.hit("slot_step", key)
        tables = jnp.asarray(self.page_tables[:, :P])
        d0 = jnp.asarray(self._dirty_start(
            P, self._n_dirty(window * K + 1)))
        t0 = time.perf_counter()
        with self._exact(), step_annotation():
            outs, cs, ms, self._pool, self._draft_pool = fn(
                self._pool, self._draft_pool, tables, d0,
                jnp.asarray(self.tokens), jnp.asarray(self.positions),
                jnp.asarray(self.next_index), jnp.asarray(self.keys),
                jnp.asarray(self.temps), jnp.asarray(self.top_ks),
                jnp.asarray(self.top_ps), jnp.asarray(self.spec_ks))
            # Sync inside the marker — see the plain step.
            outs = np.asarray(jax.device_get(outs))
            cs = np.asarray(jax.device_get(cs))
            ms = np.asarray(jax.device_get(ms))
        self.last_step_device_s = time.perf_counter() - t0
        rows = np.arange(self.n_slots)
        adv = cs.sum(axis=0).astype(np.int32)
        self.tokens = outs[-1, rows, cs[-1] - 1].astype(np.int32)
        self.positions = self.positions + adv
        self.next_index = self.next_index + adv
        if self._free:
            idle = np.asarray(self._free, np.int32)
            self.tokens[idle] = 0
            self.positions[idle] = 0
            self.next_index[idle] = 0
        return outs, cs, ms
