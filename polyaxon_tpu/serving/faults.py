"""Deterministic, seeded fault injection for the serving stack.

Crash-only serving needs a way to PROVE its recovery machinery: a
production engine meets step-program exceptions, page-pool
exhaustion, wedged devices, dead engine threads, broken prefix
stores, and clients whose sockets reset mid-response — but none of
those arrive on demand in a test, and a flaky reproduction is worse
than none.  This module is the demand side: a :class:`FaultPlan` is
a SEEDED, site-keyed schedule of injected faults (armed via ``ptpu
serve --fault-plan f.json`` / ``ModelServer(fault_plan=...)``) whose
firing pattern is a pure function of the plan — two runs of the same
plan against the same traffic inject the same faults at the same
probes, which is what lets tests/test_faults.py pin the hard
property: under an active fault plan, every SURVIVING request's
tokens are bitwise identical to the fault-free run.

Probe sites (each one ``if self.faults is not None:`` — one
attribute check — when disarmed):

=================  ========================================================
site               where it fires / what it simulates
=================  ========================================================
``step``           the engine's decode-step dispatch.  ``kind``
                   selects the failure class: ``transient`` (raises
                   :class:`TransientFault` — the bounded-retry path)
                   or ``poisoned`` (raises
                   :class:`PoisonedComputation` whenever the target
                   request — ``request_index``/``rid`` — is resident:
                   the quarantine-bisection path)
``page_alloc``     paged-KV admission (raises a
                   :class:`PageExhausted` subclass — the existing
                   requeue-and-resume path)
``slow_step``      sleeps ``delay_s`` before the dispatch (stall /
                   hung-step simulation; long delays exercise the
                   stall watchdog)
``engine_death``   the engine loop itself (raises
                   :class:`EngineDeath` OUTSIDE tick containment —
                   the supervised-restart path, serving/recovery.py)
``prefix_store``   prefix-cache lookup/store (raises
                   :class:`FaultInjected` — the degradation-ladder
                   path: the store disables itself with a counter)
``socket_reset``   the HTTP handler's response write (raises
                   :class:`SocketReset` — the connection drops
                   without a response)
``telemetry``      the engine's span/instant emission (raises
                   :class:`FaultInjected` — must stay ISOLATED:
                   counted, never request-fatal)
``replica_kill``   FLEET site (polled by the router tier,
                   serving/router.py): hard-kill replica ``replica``
                   — listener closed, in-flight connections reset —
                   the failover-and-resume path
``replica_hang``   FLEET site: replica ``replica`` stops answering
                   (connections accepted, never served) — the probe-
                   timeout / hedged-request path
``replica_slow``   FLEET site: replica ``replica`` slow-walks every
                   request by ``delay_s`` — the tail-amplification
                   pathology (arXiv:2011.03641) hedging absorbs
=================  ========================================================

Fleet sites are POLLED (:meth:`FaultPlan.poll`), not raised: the
router consumes the fired spec and applies the fault to the target
replica, so a seeded fleet chaos plan stays a pure function of the
plan + the routed-request probe order.

Plan schema (JSON)::

    {"seed": 7,
     "faults": [
       {"site": "step", "kind": "transient", "times": 2},
       {"site": "step", "kind": "poisoned", "request_index": 3},
       {"site": "page_alloc", "p": 0.1, "times": 4},
       {"site": "slow_step", "delay_s": 0.5, "after": 10, "times": 1},
       {"site": "engine_death", "after": 20, "times": 1}
     ]}

Per-spec gates, applied in order at each probe: ``after`` (skip the
first N eligible probes), ``every`` (fire on every Nth eligible probe
past ``after``), ``p`` (probability, drawn from the spec's own
seeded ``random.Random`` — deterministic in probe order), ``times``
(max fires; ``null``/absent = unbounded).  ``poisoned`` specs are
additionally gated on their target request being RESIDENT in the
failing dispatch (``request_index`` counts engine submissions,
0-based; ``rid`` matches an explicit request ID) — which is exactly
the property quarantine bisection isolates.

Injection is a TESTING tool: the plan object also carries the
``faults_injected`` counters every surface reports
(``ptpu_serving_faults_injected_total{site=...}``), so a chaos run's
evidence — what fired, where, how often — rides the same
/metrics - /info - /debug/state no-drift contract as everything else.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .paged import PageExhausted

__all__ = ["FaultPlan", "FaultSpec", "FaultInjected", "TransientFault",
           "PoisonedComputation", "EngineDeath", "SocketReset",
           "InjectedPageExhausted", "SITES", "FLEET_SITES",
           "is_transient", "is_poisoned"]

SITES = ("step", "page_alloc", "slow_step", "engine_death",
         "prefix_store", "socket_reset", "telemetry",
         "replica_kill", "replica_hang", "replica_slow")

# Sites consumed by POLLING (the router tier applies the fault to a
# replica) instead of by raising at the probe.
FLEET_SITES = ("replica_kill", "replica_hang", "replica_slow")


class FaultInjected(RuntimeError):
    """Base class for every injected fault (``injected`` marks the
    exception as harness-made, so containment code can assert it
    never leaks to a client as-is)."""

    injected = True


class TransientFault(FaultInjected):
    """An injected step failure that a bounded retry should absorb
    (the real-world analogues: a transient runtime error, a
    preempted device, a hiccuping interconnect)."""

    ptpu_transient = True


class PoisonedComputation(FaultInjected):
    """An injected step failure tied to ONE resident request — the
    co-tenancy pathology (arXiv:2011.03641) where a single poisoned
    input must not take down its batch neighbors.  Carries the
    target ``rid``."""

    ptpu_poisoned = True

    def __init__(self, msg: str, rid: Optional[str] = None):
        super().__init__(msg)
        self.rid = rid


class EngineDeath(FaultInjected):
    """Raised at the ``engine_death`` site, in the engine loop
    OUTSIDE tick's containment — the whole-engine crash the
    supervisor (serving/recovery.py) exists to survive."""


class SocketReset(FaultInjected):
    """Raised at the handler's response write: the connection is
    closed without a response, simulating a client/socket death at
    the worst moment."""


class InjectedPageExhausted(PageExhausted, FaultInjected):
    """Injected page-pool allocation failure.  Subclasses
    :class:`PageExhausted` so it rides the engine's existing
    transient-shortage path: the admission requeues and resumes
    token-identically instead of failing."""


def is_transient(err: BaseException) -> bool:
    """Classify a step failure as TRANSIENT (bounded-retry-worthy):
    the injected marker, or anything that opted in via a
    ``ptpu_transient`` attribute."""
    return bool(getattr(err, "ptpu_transient", False))


def is_poisoned(err: BaseException) -> bool:
    """Classify a step failure as POISONED (request-tied): the
    injected marker, or a ``ptpu_poisoned`` attribute."""
    return bool(getattr(err, "ptpu_poisoned", False))


class FaultSpec:
    """One parsed plan entry.  Validation is eager (a typo'd site
    must fail at plan load, not silently never fire)."""

    __slots__ = ("site", "kind", "p", "after", "every", "times",
                 "request_index", "rid", "delay_s", "replica",
                 "probes", "fired", "target_rid", "_rng")

    def __init__(self, entry: Dict[str, Any], seed: int, index: int):
        if not isinstance(entry, dict):
            raise ValueError(f"fault spec must be an object; got "
                             f"{type(entry).__name__}")
        unknown = set(entry) - {"site", "kind", "p", "after", "every",
                                "times", "request_index", "rid",
                                "delay_s", "replica"}
        if unknown:
            raise ValueError(
                f"unknown fault-spec field(s) {sorted(unknown)} "
                f"(known: site/kind/p/after/every/times/"
                f"request_index/rid/delay_s/replica)")
        site = entry.get("site")
        if site not in SITES:
            raise ValueError(
                f"fault site must be one of {SITES}; got {site!r}")
        self.site = site
        kind = entry.get("kind")
        if site == "step":
            kind = kind if kind is not None else "transient"
            if kind not in ("transient", "poisoned"):
                raise ValueError(
                    f"step fault kind must be 'transient' or "
                    f"'poisoned'; got {kind!r}")
        elif kind is not None:
            raise ValueError(
                f"'kind' only applies to site 'step' (got kind="
                f"{kind!r} on site {site!r})")
        self.kind = kind
        self.p = float(entry.get("p", 1.0))
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1]; got "
                             f"{self.p}")
        self.after = int(entry.get("after", 0))
        self.every = int(entry.get("every", 1))
        if self.after < 0 or self.every < 1:
            raise ValueError(
                f"fault after must be >= 0 and every >= 1; got "
                f"after={self.after}, every={self.every}")
        times = entry.get("times")
        self.times = int(times) if times is not None else None
        if self.times is not None and self.times < 1:
            raise ValueError(f"fault times must be >= 1; got "
                             f"{self.times}")
        ri = entry.get("request_index")
        self.request_index = int(ri) if ri is not None else None
        self.rid = entry.get("rid")
        if self.kind == "poisoned" and self.request_index is None \
                and self.rid is None:
            raise ValueError(
                "a poisoned step fault needs its target: "
                "request_index (Nth engine submission, 0-based) or "
                "rid (explicit request ID)")
        self.delay_s = float(entry.get("delay_s", 0.05))
        if site in ("slow_step", "replica_slow") and self.delay_s <= 0:
            raise ValueError(
                f"{site} delay_s must be > 0; got {self.delay_s}")
        # FLEET sites target a replica by index (the router resolves
        # it modulo its fleet size, so one plan runs on any fleet).
        rep = entry.get("replica")
        if rep is not None and site not in FLEET_SITES:
            raise ValueError(
                f"'replica' only applies to fleet sites "
                f"{FLEET_SITES} (got replica={rep!r} on site "
                f"{site!r})")
        if site in FLEET_SITES and rep is None:
            raise ValueError(
                f"fleet fault site {site!r} needs its target: "
                f"'replica' (fleet index, 0-based)")
        self.replica = int(rep) if rep is not None else None
        if self.replica is not None and self.replica < 0:
            raise ValueError(f"fault replica must be >= 0; got "
                             f"{self.replica}")
        # Live state: eligible-probe count, fire count, and the
        # resolved target rid for request_index-keyed poisoned specs.
        self.probes = 0
        self.fired = 0
        self.target_rid: Optional[str] = self.rid
        # Per-spec seeded stream: probability draws are a pure
        # function of (plan seed, spec index, probe ordinal) — the
        # determinism the whole harness is for.
        self._rng = random.Random((int(seed) * 1000003) ^ index)

    def describe(self) -> Dict[str, Any]:
        return {"site": self.site,
                **({"kind": self.kind} if self.kind else {}),
                **({"p": self.p} if self.p < 1.0 else {}),
                **({"after": self.after} if self.after else {}),
                **({"every": self.every} if self.every > 1 else {}),
                **({"times": self.times}
                   if self.times is not None else {}),
                **({"request_index": self.request_index}
                   if self.request_index is not None else {}),
                **({"rid": self.rid} if self.rid else {}),
                **({"replica": self.replica}
                   if self.replica is not None else {}),
                "fired": self.fired}


class FaultPlan:
    """The armed fault schedule + its injection counters.

    Thread-safe: probes arrive from the engine thread AND handler
    threads (socket/prefix sites).  The ``slow_step`` sleep happens
    OUTSIDE ``_plan_lock`` so a long injected stall can never block a
    concurrent probe (or a /metrics read of the counters).
    """

    def __init__(self, plan: Dict[str, Any]):
        if not isinstance(plan, dict):
            raise ValueError(
                f"fault plan must be an object with 'faults'; got "
                f"{type(plan).__name__}")
        unknown = set(plan) - {"seed", "faults"}
        if unknown:
            raise ValueError(
                f"unknown fault-plan field(s) {sorted(unknown)} "
                f"(known: seed, faults)")
        self.seed = int(plan.get("seed", 0))
        entries = plan.get("faults")
        if not isinstance(entries, list) or not entries:
            raise ValueError(
                "fault plan needs a non-empty 'faults' list")
        self.specs: List[FaultSpec] = [
            FaultSpec(e, self.seed, i) for i, e in enumerate(entries)]
        self._plan_lock = threading.Lock()
        self.injected: Dict[str, int] = {}
        self.injected_total = 0
        self.last_site: Optional[str] = None
        self.last_fault_t: Optional[float] = None
        self._submit_ordinal = 0

    @classmethod
    def load(cls, source) -> "FaultPlan":
        """A plan from a dict, a JSON file path, or a FaultPlan
        (pass-through) — the one constructor every arming surface
        (--fault-plan, ModelServer(fault_plan=...)) goes through."""
        if isinstance(source, cls):
            return source
        if isinstance(source, dict):
            return cls(source)
        with open(source) as f:
            return cls(json.load(f))

    # -- wiring ----------------------------------------------------------

    def on_submit(self, rid: Optional[str]) -> None:
        """Called by ``engine.submit`` for every accepted request:
        resolves ``request_index``-keyed poisoned specs to the
        concrete request ID they will fire on."""
        with self._plan_lock:
            ordinal = self._submit_ordinal
            self._submit_ordinal += 1
            for spec in self.specs:
                if spec.kind == "poisoned" \
                        and spec.request_index == ordinal \
                        and spec.target_rid is None:
                    spec.target_rid = rid

    # -- the probe -------------------------------------------------------

    def _gates_pass(self, spec: FaultSpec) -> bool:
        """after/every/p gating for one eligible probe (mutates the
        spec's probe counter and draws from its seeded stream; the
        caller holds ``_plan_lock``)."""
        spec.probes += 1
        if spec.probes <= spec.after:
            return False
        if spec.every > 1 and \
                (spec.probes - spec.after - 1) % spec.every != 0:
            return False
        if spec.p < 1.0 and spec._rng.random() >= spec.p:
            return False
        return True

    def _note_fired(self, spec: FaultSpec) -> None:
        """Injection bookkeeping (caller holds ``_plan_lock``)."""
        spec.fired += 1
        self.injected[spec.site] = self.injected.get(spec.site, 0) + 1
        self.injected_total += 1
        self.last_site = spec.site
        self.last_fault_t = time.time()

    def check(self, site: str,
              rids: Optional[Sequence[Optional[str]]] = None) -> None:
        """One probe at ``site``: raise the site's injected fault
        when a spec's gates line up (or sleep, for ``slow_step``).
        ``rids`` (step site) is the resident request-ID set the
        poisoned gate matches against."""
        to_fire: Optional[FaultSpec] = None
        delay = 0.0
        with self._plan_lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.times is not None \
                        and spec.fired >= spec.times:
                    continue
                if spec.kind == "poisoned":
                    tgt = spec.target_rid
                    if tgt is None or rids is None or tgt not in rids:
                        continue
                if not self._gates_pass(spec):
                    continue
                self._note_fired(spec)
                if site == "slow_step":
                    delay = max(delay, spec.delay_s)
                    continue        # a sleep composes with a raise
                to_fire = spec
                break
        if delay > 0.0:
            # Outside the plan lock (and the caller keeps it outside
            # the device lock): an injected stall must stall the
            # ENGINE LOOP, not every thread that touches the plan.
            time.sleep(delay)
        if to_fire is not None:
            raise self._exception_for(to_fire)

    def poll(self, site: str) -> Optional[Dict[str, Any]]:
        """One probe at a FLEET site: return the fired fault as a
        dict (``{"site", "replica", "delay_s"}``) for the caller —
        the router tier — to APPLY to the target replica, or None.
        Polling, not raising: a replica fault is an action against
        fleet state, not an exception on the probing thread.  Same
        gates and counters as :meth:`check`, so a fleet plan's fire
        pattern stays a pure function of (plan, probe order)."""
        if site not in FLEET_SITES:
            raise ValueError(
                f"poll() takes a fleet site {FLEET_SITES}; got "
                f"{site!r} (exception sites go through check())")
        with self._plan_lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.times is not None \
                        and spec.fired >= spec.times:
                    continue
                if not self._gates_pass(spec):
                    continue
                self._note_fired(spec)
                return {"site": site, "replica": spec.replica,
                        "delay_s": spec.delay_s}
        return None

    @staticmethod
    def _exception_for(spec: FaultSpec) -> BaseException:
        if spec.site == "step":
            if spec.kind == "poisoned":
                return PoisonedComputation(
                    f"injected poisoned computation (target request "
                    f"{spec.target_rid})", rid=spec.target_rid)
            return TransientFault(
                "injected transient step fault")
        if spec.site == "page_alloc":
            return InjectedPageExhausted(
                "injected page-pool allocation failure")
        if spec.site == "engine_death":
            return EngineDeath("injected engine-thread death")
        if spec.site == "socket_reset":
            return SocketReset("injected handler socket reset")
        if spec.site == "prefix_store":
            return FaultInjected("injected prefix-store error")
        return FaultInjected(f"injected {spec.site} fault")

    # -- introspection ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The counters every surface reports (engine.stats() embeds
        this; /metrics renders the per-site split as
        ``ptpu_serving_faults_injected_total{site=...}``)."""
        with self._plan_lock:
            return {
                "fault_seed": self.seed,
                "fault_specs": len(self.specs),
                "faults_injected_total": self.injected_total,
                "faults_injected": dict(self.injected),
                **({"last_fault_site": self.last_site}
                   if self.last_site is not None else {}),
                **({"last_fault_t": round(self.last_fault_t, 3)}
                   if self.last_fault_t is not None else {}),
            }

    def describe(self) -> List[Dict[str, Any]]:
        with self._plan_lock:
            return [s.describe() for s in self.specs]
