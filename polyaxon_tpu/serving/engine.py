"""Continuous-batching decode engine.

Replaces the request-coalescing path (whole ``generate()`` calls
merged per compile shape) with STEP-LEVEL scheduling: a fixed pool of
decode slots (slots.py) advances one token per tick, and the gaps the
old design wasted are reclaimed at step boundaries —

- a request hitting EOS (or its budget) frees its slot the same step,
  instead of decoding frozen eos tokens until the longest batch
  member finishes;
- a queued request is admitted into a free slot between two decode
  steps, instead of waiting for the whole running batch to drain;
- long prompts prefill in bounded chunks INTERLEAVED between decode
  steps (one chunk per boundary while decodes run), so a 2k-token
  prompt delays resident requests by one chunk forward, not a full
  prefill.

This is the decoupling of logical workload from physical batch that
VirtualFlow (arXiv:2009.09523) argues for, applied to the decode
loop.  Greedy AND sampled (non-beam, non-speculative) requests share
one slot pool and one compiled step program: per-slot greedy argmax
is exact (rows never interact, eos-frozen rows pad to budget —
identical to solo ``generate``, pinned in tests/test_serving.py),
and sampled slots draw through the POSITION-KEYED RNG contract
(models/generate): a stream's i-th token key is
``fold_in(fold_in(PRNGKey(seed), row), i)`` — a function of (seed,
row, token index) only, never of slot id, engine step count, or
co-tenancy — so sampled output is bit-identical to the solo
``generate_positional`` reference under any admission schedule
(pinned in tests/test_sampled_engine.py).  SPECULATIVE requests are
engine citizens too when the engine owns a draft model: spec slots
draft/verify/commit a variable accepted prefix per round through the
spec step program (slots.py), every draft/accept/residual draw
position-keyed per (token index, lane), so speculative output is
bit-identical to ``generate_speculative``'s seed mode under any
co-tenancy (pinned in tests/test_spec_engine.py).  Beam requests
keep the solo path (the per-beam cache tiling/reorder is a layout
the slot pool doesn't speak).

Threading: ``submit`` may be called from any handler thread; all slot
and queue mutation happens on the engine loop thread (or, in tests,
via manual ``tick()`` calls with the loop not started — never both).
Device work (prefill chunks, decode steps) runs under ``device_lock``
shared with the solo path, so engine ticks and solo requests
interleave at step granularity.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import traceback
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ._lru import lru_get
from .debug import SnapshotBoard, events_to_dicts, new_request_id
from .faults import is_poisoned, is_transient
from .forensics import compute_ledger
from .paged import PageExhausted
from .recovery import RetryPolicy
from .scheduler import (AdmissionQueue, DeadlineExceeded, PRIORITIES,
                        PoisonedRequest, QueueFullError,
                        RequestCancelled, RequestGroup, SamplingSpec,
                        SchedulerPolicy, ShedError, Stream,
                        terminal_status)
from .slots import SlotKVManager
from .telemetry import ENGINE_PID, Histogram, Telemetry

__all__ = ["DecodeEngine", "QueueFullError", "SPEC_ACCEPT_BUCKETS"]

# Acceptance-rate histogram bucket upper bounds (le) for completed
# speculative requests; the last implicit bucket is +Inf.
SPEC_ACCEPT_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


class DecodeEngine:
    def __init__(self, model, variables, *,
                 policy: Optional[SchedulerPolicy] = None,
                 device_lock: Optional[threading.Lock] = None,
                 autostart: bool = True,
                 prefill_fns=None,
                 draft_model=None, draft_variables=None,
                 telemetry: Optional[Telemetry] = None,
                 sentinel=None, mesh=None, faults=None,
                 retry_policy: Optional[RetryPolicy] = None):
        # Serving mesh (serving/meshed.py): accepts a ServingMesh, a
        # spec string ("tp=4"), a dict, or a MeshSpec.  When set, the
        # slot KV pools shard over the mesh, params are PLACED onto
        # it (library callers who didn't pre-place get the exact
        # layout applied here; ModelServer places before
        # constructing the engine and passes a ServingMesh whose
        # placement this re-application matches, so double placement
        # is a no-op), and every engine-owned trace runs under the
        # serving-exact constraint mode — output stays token-bitwise
        # identical to the unmeshed engine per seed.
        if mesh is not None:
            from .meshed import ServingMesh

            if not isinstance(mesh, ServingMesh):
                mesh = ServingMesh(mesh)
            mesh.validate_model(model, "model",
                                n_slots=(policy or SchedulerPolicy()
                                         ).n_slots)
            if draft_model is not None:
                mesh.validate_model(draft_model, "draft model")
            variables = mesh.place_params(variables)
            if draft_variables is not None:
                draft_variables = mesh.place_params(draft_variables)
        self.mesh = mesh
        self.model = model
        self.variables = variables
        # Telemetry ring shared with the owning server (ModelServer
        # passes its own, so request spans and engine step records
        # land in ONE /trace timeline); a standalone engine defaults
        # to a disabled core — every record call is one attribute
        # check, nothing else.
        self.tel = telemetry if telemetry is not None \
            else Telemetry(buffer=0)
        # Draft model: enables SPECULATIVE streams (spec_k > 0) — the
        # slot pool stacks a second cache for it and the spec step
        # variant drafts/verifies/commits per round.
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        self.policy = policy or SchedulerPolicy()
        self.device_lock = device_lock or threading.Lock()
        # Recompile sentinel (analysis/recompile.py): every program-
        # cache miss across the engine's prefill/step/insert caches is
        # counted (and trace-marked), so the zero-steady-state-
        # recompile contract is testable.  ModelServer passes ITS
        # sentinel so server and engine caches report as one.
        if sentinel is None:
            from ..analysis.recompile import RecompileSentinel

            sentinel = RecompileSentinel(telemetry=self.tel)
        self.sentinel = sentinel
        # autostart=False: no loop thread — the owner drives tick()
        # manually (deterministic tests, offline batch use).
        self.autostart = bool(autostart)
        # KV storage: the fixed-lane stacked pool (slots.py), or —
        # policy.kv_paged — the block-table page pool (paged.py):
        # per-request page reservations instead of max_position
        # lanes, so occupancy under mixed-length traffic is bounded
        # by token usage, not by the widest request.
        self.paged = bool(self.policy.kv_paged)
        if self.paged:
            from .paged import PagedSlotKVManager

            max_pos = getattr(getattr(model, "cfg", None),
                              "max_position", None)
            if max_pos is None or getattr(
                    getattr(model, "cfg", None), "kv_cache_ring",
                    False):
                raise ValueError(
                    "kv_paged needs a decoder-only model with a "
                    "plain/int8 max_position cache (ring caches keep "
                    "the fixed-lane manager)")
            self.slots = PagedSlotKVManager(
                model, variables, self.policy.n_slots,
                page_tokens=self.policy.kv_page_tokens,
                n_pages=self.policy.kv_pages,
                max_position=max_pos,
                decode_window=self.policy.decode_window,
                spec_k_cap=self.policy.spec_k_cap,
                lazy=self.policy.kv_lazy,
                draft_model=draft_model,
                draft_variables=self.draft_variables,
                sentinel=sentinel, mesh=mesh)
        else:
            self.slots = SlotKVManager(model, self.variables,
                                       self.policy.n_slots,
                                       draft_model=draft_model,
                                       draft_variables=self.draft_variables,
                                       sentinel=sentinel, mesh=mesh)
        # Optional page-pressure relief hook (paged mode): called
        # with the page deficit when an admit-ready stream is blocked
        # on free pages; the server wires it to prefix-cache LRU
        # eviction so stored-but-idle prefixes yield to live traffic.
        self.page_reclaim = None
        self.queue = AdmissionQueue(self.policy)
        # streams resident in a slot: slot index -> Stream
        self._resident: Dict[int, Stream] = {}
        # prefill/extend programs keyed by piece length (LRU-bounded:
        # remainder pieces vary with prompt length).  ``prefill_fns``
        # ((s_len, first) -> jitted fn) lets an owner share ONE
        # compile cache — ModelServer passes its _split_fns so engine
        # traffic and /prefill never compile the same program twice.
        self._prefill_fns = prefill_fns
        self._pf_fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        # Draft prefill programs (speculative streams prefill through
        # BOTH models): engine-owned — the server's shared cache only
        # speaks the target model.
        self._pf_fns_draft: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._pf_cap = 16
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        self._wake = threading.Condition()
        self._stop = False
        # jitted first-token sampler for sampled admissions (token
        # index 0, drawn from the prefill logits) — compiled once,
        # shared by every stream
        self._admit_sample_fn = None
        # counters (read unlocked by metrics — monotonic ints);
        # admitted/completed split by mode so pool utilization under
        # mixed greedy/sampled load is observable
        self.admitted_total = 0
        self.admitted_greedy_total = 0
        self.admitted_sampled_total = 0
        self.admitted_spec_total = 0
        self.evicted_total = 0
        self.decode_steps_total = 0
        self.prefill_chunks_total = 0
        self.completed_total = 0
        self.completed_greedy_total = 0
        self.completed_sampled_total = 0
        self.completed_spec_total = 0
        # Speculative scheduling counters + the per-request
        # acceptance-rate histogram (accepted drafts / drafted, bucket
        # upper bounds in SPEC_ACCEPT_BUCKETS; one completed request =
        # one observation).  ONE shared telemetry.Histogram — /metrics
        # and /info both render engine.stats(), so they can never
        # drift, and the exposition goes through the same
        # render_histogram helper as the latency histograms.
        self.spec_rounds_total = 0
        self.spec_drafted_total = 0
        self.spec_accepted_total = 0
        self.spec_accept = Histogram(SPEC_ACCEPT_BUCKETS)
        # Request-lifecycle counters (one bump per terminal REQUEST,
        # not per stream) + the per-class admission split.  Mostly
        # mutated by the sweep/preemption machinery on the engine
        # thread; the SHED counters are also bumped from submitter
        # threads (the draining gate), so those go under _shed_lock —
        # /metrics reads everything unlocked like the rest.
        self._shed_lock = threading.Lock()
        self.cancelled_total = 0
        self.expired_total = 0
        self.shed_total = 0
        self.shed_by_class = {p: 0 for p in PRIORITIES}
        # Paged-KV shed split: requests whose page budget can never
        # fit the pool (503 reason kv_pages) — a sizing signal, kept
        # separate from queue-deadline/draining sheds.
        self.shed_kv_pages_total = 0
        # LAZY-KV exhaustion preemptions (engine._ensure_lazy_growth):
        # a resident evicted mid-decode because a co-tenant's page
        # growth found the pool empty — the concurrency-vs-memory
        # trade the --kv-lazy mode makes explicit.  ``_exhaust_bars``
        # holds the evictees whose re-admission is barred until the
        # blocked growth completes (the livelock guard).
        self.kv_preempt_exhaustion_total = 0
        self._exhaust_bars: list = []
        self.preempted_total = 0
        self.resumed_total = 0
        self.admitted_by_class = {p: 0 for p in PRIORITIES}
        # Preemption control signal: a SLIDING WINDOW of the most
        # recent interactive admission-anchored TTFTs (the same
        # observations the exported ttft_interactive histogram gets).
        # The controller reads p99 over THIS window, not the
        # cumulative histogram — lifetime bucket counts never decay,
        # so one bad period would otherwise latch aggressive batch
        # preemption until process restart.
        from collections import deque
        self._ttft_recent: "deque[float]" = deque(maxlen=64)
        # Sweep fast path: the boundary sweep scans residents + the
        # whole queue, which is pure waste for deployments that never
        # touch the lifecycle features.  ``_cancel_pending`` is set
        # by cancel() and consumed by the next sweep;
        # ``_deadline_armed`` goes (and stays) True once ANY
        # deadline-bearing request has been submitted — sticky on
        # purpose: a deployment using deadlines pays the sweep as the
        # feature's cost, one that never does skips it entirely.
        self._cancel_pending = False
        self._deadline_armed = False
        # Draining: stop ADMITTING new requests (submit sheds with
        # 503), finish everything already accepted — the /drain
        # endpoint's engine half.  One-way per engine lifetime.
        self.draining = False
        # Meshed step accounting: cumulative device wall (dispatch +
        # sync, from the manager's last_step_device_s) vs scheduling
        # wall per decode dispatch — a host-clock ESTIMATE of device
        # time; the flight recorder below is the device-truth
        # counterpart.
        self.step_device_s_total = 0.0
        self.step_wall_s_total = 0.0
        # Flight recorder (serving/profiling.py): set by the owning
        # server when --profile-every is armed.  None (the default)
        # keeps the decode loop's cost at one attribute check per
        # dispatch; armed, the recorder periodically wraps
        # profile_steps dispatch boundaries in a jax.profiler window
        # and publishes trace-true attribution (collective/host-gap/
        # busy shares, serving MFU) to /metrics + /profile/report.
        self.recorder = None
        # Request-scoped debuggability (serving/debug.py).
        # ``history``: the terminal-record retention ring behind
        # GET /requests — None (library default) records nothing; the
        # server wires its RequestHistory here before traffic.
        # ``debug_board``: the published step-boundary snapshot
        # behind GET /debug/state; ``last_boundary_t`` is the stall
        # watchdog's progress signal (stamped at the end of every
        # tick).  ``_last_page_free`` attributes a blocked
        # admission's eventual unblock to the eviction that freed
        # capacity — (request id, why) of the most recent release.
        self.history = None
        # ``forensics``: the server's ForensicsCore (phase
        # accumulator + anomaly sentry, serving/forensics.py), or
        # None — terminal paths feed it the request's phase ledger;
        # disarmed it is one attribute check.
        self.forensics = None
        self.debug_board = SnapshotBoard()
        self.last_boundary_t = time.perf_counter()
        self._last_page_free: Optional[Tuple] = None
        # Publishing is throttled to one build per interval: a busy
        # pool crosses hundreds of step boundaries a second, and
        # /debug/state only needs a recent-consistent snapshot, not
        # an every-boundary one — the snapshot build (slot + queue
        # dicts) must not become a per-step tax nobody asked for.
        self.board_interval_s = 0.1
        self._board_t = 0.0
        # FAULT TOLERANCE (serving/faults.py + serving/recovery.py).
        # ``faults``: the armed FaultPlan, or None (the default) —
        # every probe site is one attribute check when disarmed.
        # ``retry_policy``: the bounded jittered-backoff schedule
        # step-level TRANSIENT failures retry under (shared shape
        # with the supervisor's restart backoff).  ``supervisor``:
        # set by recovery.EngineSupervisor — when attached, a crash
        # escaping the scheduling layer restarts the loop and
        # requeues everything for token-identical resume instead of
        # failing every caller; ``down`` latches True while the
        # crash-storm circuit breaker holds the engine offline (new
        # submits shed 503 ``engine_down``; /healthz reports it).
        # ``_suspects``: groups implicated by a poisoned step
        # dispatch, pending exoneration or conviction (the
        # quarantine-bisection state, _quarantine_step).
        self.faults = faults
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy()
        self.supervisor = None
        self.down = False
        self._suspects: set = set()
        # Convictions since the last SUCCESSFUL dispatch: a fault
        # that keeps failing across quarantine convictions tracks
        # the ENGINE, not a request — after 2 such convictions the
        # next episode escalates to supervised recovery instead of
        # serially convicting innocents (reset only by a dispatch
        # that works, so a post-restart recurrence escalates
        # immediately).
        self._convictions_without_success = 0
        self.step_retries_total = 0
        self.requests_requeued_total = 0
        self.poisoned_total = 0
        self.telemetry_errors_total = 0
        self.debug_board.publish(self.build_debug_snapshot())

    def _exact(self):
        """Serving-exact trace context for engine-owned device calls
        (prefill pieces trace over column-sharded params); no-op
        unmeshed."""
        return self.mesh.exact() if self.mesh is not None \
            else contextlib.nullcontext()

    # -- submission (any thread) ----------------------------------------

    def submit(self, rows: np.ndarray, new: int,
               eos_id: Optional[int], prefill_chunk: Optional[int],
               *, sampling: Optional[SamplingSpec] = None,
               prefix=None, on_prefilled=None,
               record_timings: bool = False,
               priority: Optional[str] = None,
               deadline_s: Optional[float] = None,
               shared_pages=None,
               rid: Optional[str] = None,
               prefix_info=None,
               pre_events=None,
               resume_tokens: int = 0) -> RequestGroup:
        """Enqueue a request (may raise QueueFullError) and make sure
        the loop is running.  Returns the group; callers block on
        ``group.event``.  ``sampling`` carries the per-request
        (seed, temperature, top_k, top_p) — None (or temperature 0)
        is greedy; sampled streams draw through the position-keyed
        RNG contract, so their tokens are independent of co-tenancy.

        ``prefix=(p_cached, logits, cache)`` seeds a SINGLE-ROW request
        with an existing prefill state (the prefix-cache hit path): the
        stream starts ``p_cached`` tokens in, so it prefills only the
        suffix — or skips prefill entirely on a full-length hit — and
        decodes in a slot like any other request, instead of holding
        the device lock for a whole solo decode.  ``on_prefilled``
        fires on the engine thread once the prompt is fully consumed
        (the cache store-back hook).

        ``sampling.spec_k > 0`` submits a SPECULATIVE request: needs
        the engine's draft model (its prompt prefills through BOTH
        models), and composes with greedy or sampled accept lanes.

        ``priority`` (default: the policy's ``default_priority``)
        picks the request's class queue — ``interactive`` drains
        ahead of ``batch``, and batch residents are preemptible under
        the TTFT SLO.  ``deadline_s`` (relative seconds) arms a
        deadline: expiry evicts the request at the next step boundary
        with :class:`DeadlineExceeded`.  A DRAINING engine sheds
        every new submit with :class:`ShedError` (503).

        PAGED engines additionally shed (503 ``reason: kv_pages``) a
        request whose KV budget can NEVER fit the page pool — waiting
        would deadlock, not resolve — while one that merely doesn't
        fit RIGHT NOW queues until evictions free pages.
        ``shared_pages`` (single-row prefix hits only) are PINNED
        page ids of the stored prefix's full pages: the engine owns
        the pins from here on, maps them read-only into the stream's
        table at admission, and releases them on any pre-admission
        terminal path.

        ``rid`` is the request's correlation ID (the server passes
        the inbound/generated ``X-Request-Id``); None generates one,
        so EVERY group carries an ID into its trace spans and its
        request-history record.  ``prefix_info`` rides the history
        record as prefix-cache hit provenance.  ``pre_events`` are
        span tuples the CALLER paid before submit (a fleet wire
        fetch): prepended to the stream's timeline so the history
        record and the ``timings`` block attribute that cost to this
        request.

        ``resume_tokens=N`` (single-row) declares the trailing N
        prompt tokens a PRIOR attempt's committed output — the
        cross-replica resume contract (docs/DESIGN.md): a router
        failing a request over replays ``prompt ++
        tokens_received_so_far`` and the stream re-enters through
        the SAME preempt-resume machinery PR 6 pinned (re-prefill of
        the committed prefix, re-admission feeding ``out[-1]`` with
        ``next_index == len(out)``), so sampled draws continue at
        position key N exactly as the uninterrupted run — on ANY
        replica — would have drawn them.  ``new`` stays the
        request's ORIGINAL total budget; the group's result is the
        original prompt plus all ``new`` tokens."""
        if priority is None:
            priority = self.policy.default_priority
        if priority not in PRIORITIES:
            # Validate before the draining gate uses it as a counter
            # key (RequestGroup would catch it later anyway; a bad
            # priority must be a ValueError, never a KeyError).
            raise ValueError(f"priority must be one of {PRIORITIES};"
                             f" got {priority!r}")
        if self.draining:
            # Counted here too: the server's drain gate catches HTTP
            # traffic, but a library caller (or a request that raced
            # /drain past the server check) still sheds — and must
            # still show up in the shed metrics.  Under _shed_lock:
            # submit runs on arbitrary threads, unlike the sweep.
            with self._shed_lock:
                self.shed_total += 1
                self.shed_by_class[priority] += 1
            raise ShedError(
                "engine is draining: finishing in-flight requests, "
                "admitting none", reason="draining")
        if self.down:
            # Crash-storm circuit breaker open (recovery.py): shed
            # fast with the machine-readable reason instead of
            # queueing work a dead engine will never drain — the
            # supervisor's cooldown probe flips this back off.
            with self._shed_lock:
                self.shed_total += 1
                self.shed_by_class[priority] += 1
            raise ShedError(
                "decode engine is down (crash-restart circuit "
                "breaker open); retry after the cooldown",
                reason="engine_down",
                retry_after=self.policy.retry_after_s)
        if self.paged:
            # A resume replay carries prior output inside the prompt;
            # the slot only ever holds original-prompt + budget.
            need = self._kv_tokens_needed(
                rows.shape[1] - int(resume_tokens or 0), new)
            if need > self.slots.capacity_tokens:
                # Graceful overload, not deadlock: this request can
                # NEVER fit the pool, so queue-waiting for evictions
                # would hang it forever.  One that fits the pool but
                # not the current free set simply waits admit-ready.
                with self._shed_lock:
                    self.shed_total += 1
                    self.shed_by_class[priority] += 1
                    self.shed_kv_pages_total += 1
                raise ShedError(
                    f"request KV budget ({need} tokens/row) exceeds "
                    f"the page pool ({self.slots.capacity_tokens} "
                    f"tokens = {self.slots.n_pages} x "
                    f"{self.slots.page_tokens}-token pages); shrink "
                    f"the prompt/budget or raise --kv-pages",
                    reason="kv_pages")
            if sampling is not None \
                    and sampling.spec_k > self.policy.spec_k_cap:
                # Paged co-tenants reserved slack for at most
                # spec_k_cap-wide verify chunks; a wider resident
                # would write past their reservations.
                raise ValueError(
                    f"spec_k {sampling.spec_k} exceeds the paged "
                    f"engine's spec_k_cap {self.policy.spec_k_cap}")
        if sampling is not None and sampling.spec_k > 0:
            if self.draft_model is None:
                raise ValueError(
                    "speculative request on an engine without a "
                    "draft model")
            if prefix is not None:
                # The stored prefix holds only the TARGET's prefill;
                # a draft cache seeded from nothing would verify
                # against garbage.  The server keeps speculative
                # requests off the prefix path — enforce it here too.
                raise ValueError(
                    "speculative requests cannot seed from a prefix "
                    "cache entry (the draft cache has no stored "
                    "prefill)")
        if resume_tokens:
            # CROSS-REPLICA RESUME: the trailing N prompt tokens are
            # committed output from a prior attempt (router failover
            # replay).  Split them back out and re-enter through the
            # preempt-resume machinery — prepare_resume re-prefills
            # ``prompt ++ out[:-1]`` in pow2 pieces, and admission
            # feeds ``out[-1]`` at its original absolute position
            # with ``next_index == len(out)``, so token N draws with
            # exactly the position key an uninterrupted run uses.
            rt = int(resume_tokens)
            if prefix is not None:
                raise ValueError(
                    "resume_tokens cannot combine with a prefix-"
                    "cache seed (the replayed prefix IS the state)")
            if rows.shape[0] != 1:
                raise ValueError(
                    f"resume_tokens takes a single-row request (got "
                    f"batch {rows.shape[0]}; multi-row failover "
                    f"replays the whole request instead)")
            if rt >= rows.shape[1]:
                raise ValueError(
                    f"resume_tokens ({rt}) must leave at least one "
                    f"original prompt token (prompt length "
                    f"{rows.shape[1]})")
            if rt >= new:
                raise ValueError(
                    f"resume_tokens ({rt}) >= max_new_tokens "
                    f"({new}): nothing left to generate")
            out_prev = [int(t) for t in rows[0, rows.shape[1] - rt:]]
            if eos_id is not None and eos_id in out_prev:
                raise ValueError(
                    "resume_tokens output already contains eos_id; "
                    "the request is complete — nothing to resume")
            orig = np.ascontiguousarray(rows[:, :rows.shape[1] - rt])
            group = RequestGroup(orig, new, eos_id, [], sampling,
                                 priority=priority)
            stream = group.streams[0]
            stream.out = out_prev
            stream.prepare_resume(SchedulerPolicy.pow2_pieces(
                orig.shape[1] + rt - 1))
        elif prefix is None:
            pieces = self.policy.chunk_plan(rows.shape[1],
                                            prefill_chunk)
            group = RequestGroup(rows, new, eos_id, pieces, sampling,
                                 priority=priority)
        else:
            if rows.shape[0] != 1:
                raise ValueError(
                    "prefix-seeded submit takes a single-row request "
                    f"(got batch {rows.shape[0]})")
            p_cached, logits, cache = prefix
            suffix = rows.shape[1] - p_cached
            pieces = self.policy.chunk_plan(suffix, prefill_chunk) \
                if suffix > 0 else []
            group = RequestGroup(rows, new, eos_id, pieces, sampling,
                                 priority=priority)
            stream = group.streams[0]
            stream.filled = p_cached
            stream.logits = logits
            stream.cache = cache
        if shared_pages:
            # Single-row prefix hits only: the pins ride the stream
            # until admission transfers them into the slot table.
            # The pool epoch they were pinned under rides along —
            # if crash recovery rebuilds the pool before admission,
            # _validate_shared_epoch drops the stale ids by
            # reference instead of feeding them to the fresh
            # accounting.
            group.streams[0].kv_shared = tuple(shared_pages)
            group.streams[0].kv_epoch = getattr(
                shared_pages, "epoch", None)
        if deadline_s is not None:
            group.deadline = group.t_submit + float(deadline_s)
            self._deadline_armed = True
        group.rid = rid if rid is not None else new_request_id()
        if self.faults is not None:
            # Resolve request_index-keyed poisoned fault specs to
            # this request's concrete ID (faults.FaultPlan).
            self.faults.on_submit(group.rid)
        group.prefix_info = prefix_info
        group.on_prefilled = on_prefilled
        group.record_timings = bool(record_timings)
        # Streams collect their span tuples when the caller asked for
        # a ``timings`` block, the history ring is armed, OR the
        # forensics core is armed — the same events back all three
        # surfaces, so a record's timeline, a live timings response,
        # and the phase ledger can never disagree (a ledger computed
        # with no events would be pure unattributed wall).
        keep_events = group.record_timings or (
            self.history is not None and self.history.enabled) \
            or self.forensics is not None
        for stream in group.streams:
            stream.sid = self.tel.new_tid()
            if keep_events:
                stream.events = []
        if pre_events and keep_events and group.streams:
            # Caller-paid spans (wire fetch) lead the timeline —
            # they happened before anything the engine records.
            s0 = group.streams[0]
            s0.events = list(pre_events) + (s0.events or [])
        # Idle -> busy transition: re-stamp the watchdog's progress
        # signal, or a server that sat idle past --stall-timeout
        # would read as stalled the moment work arrives (the loop
        # only stamps at tick, and the first tick may be a
        # seconds-long compile).  Only on the transition — submits
        # into an already-busy (possibly wedged) engine must NOT
        # keep resetting staleness.
        if not self._resident and len(self.queue) == 0:
            # ptpu: lockfree[monotonic staleness stamp: torn/lost stamps only shift stall detection by one tick]
            self.last_boundary_t = time.perf_counter()
        # Queue-entry instant: the FIRST trace event a request owns,
        # so even one that never reaches admission (wedged engine,
        # stall bundle) is findable in the ring by its rid.  Emitted
        # BEFORE queue.submit — once the group is in the queue the
        # engine thread can process it immediately, and a later
        # "queued" would land out of order in stream.events.
        for stream in group.streams:
            self._emit_instant(stream, "queued", group.t_submit,
                               row=stream.row, priority=priority)
        try:
            self.queue.submit(group)      # raises when full
        except QueueFullError:
            # Close the causal story for the trace ring: submitted,
            # never queued (429 at the front-end).
            for stream in group.streams:
                self._emit_instant(stream, "shed",
                                   time.perf_counter(),
                                   row=stream.row,
                                   reason="queue_full")
            raise
        if self.autostart:
            self._ensure_thread()
            with self._wake:
                self._wake.notify()
        return group

    def generate(self, rows: np.ndarray, new: int,
                 eos_id: Optional[int],
                 prefill_chunk: Optional[int],
                 sampling: Optional[SamplingSpec] = None) -> np.ndarray:
        """Blocking submit -> [B, p_len + new] tokens (the /generate
        engine path)."""
        group = self.submit(rows, new, eos_id, prefill_chunk,
                            sampling=sampling)
        group.event.wait()
        if group.error is not None:
            raise group.error
        return group.result()

    def cancel(self, group: RequestGroup,
               err: Optional[BaseException] = None) -> None:
        """Request ``group``'s eviction (client disconnect, deadline,
        front-end give-up).  Callable from any thread; the engine
        DELIVERS it at its next step boundary — queued streams drop,
        a mid-prefill stream abandons its partial cache, resident
        streams free their slots — and the group fails with ``err``
        (default :class:`RequestCancelled`)."""
        group.request_cancel(err if err is not None
                             else RequestCancelled(
                                 "request cancelled"))
        # Flag AFTER the cancel is stored: the sweep that sees the
        # flag is guaranteed to see the cancel_error too.  Then wake
        # an idle loop so delivery doesn't wait out the idle sleep;
        # manual-tick owners just call tick().
        # ptpu: lockfree[handoff flag: writers only store True, the engine sweep clears; next boundary re-reads]
        self._cancel_pending = True
        with self._wake:
            self._wake.notify()

    def drain(self) -> None:
        """Stop admission (new submits shed with 503 ``draining``)
        while every already-accepted request — queued, prefilling, or
        resident — runs to completion.  The server half turns
        readiness off so a router stops sending traffic here."""
        self.draining = True

    # -- engine loop ----------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._thread_lock:
            t = self._thread
            if t is not None and t.is_alive():
                if not self._stop:
                    return
                # A concurrent close() is in flight: the exiting loop's
                # final drain may have run before this caller's enqueue
                # landed, which would strand the group with no thread
                # to process or fail it.  Wait the old loop out, then
                # start a fresh one that owns the queue.  (If the old
                # drain DID see the group, it failed it with "decode
                # engine closed" — an error, never a hang.)  Timed:
                # this wait runs under _thread_lock, so an old loop
                # wedged in a device call would otherwise stall every
                # submitter forever (LOCK-HOLD) — and starting a
                # second loop beside a live one would race the slot
                # state, so a timeout is a hard error instead.
                t.join(timeout=30)
                if t.is_alive():
                    raise RuntimeError(
                        "decode engine loop thread did not exit "
                        "within 30s of close(); refusing to start a "
                        "second loop over the same slot pool")
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="decode-engine",
                daemon=True)
            self._thread.start()

    def close(self) -> None:
        # Under _thread_lock so a concurrent submit's _ensure_thread
        # restart serializes against the stop-join-drain sequence:
        # its group is either failed by a drain (error, never a hang)
        # or owned by a loop thread started strictly after close.
        with self._thread_lock:
            self._stop = True
            with self._wake:
                self._wake.notify_all()
            t = self._thread
            if t is not None and t.is_alive():
                t.join(timeout=5)
            # In-flight groups must fail (a generate() caller blocked
            # on group.event has to wake with an error, not wait
            # forever), but _resident and the slot free-list are
            # loop-thread state: a live loop thread (join timed out
            # mid-device-call) drains them itself on exit — see _loop
            # — so only drain here when no loop thread can race us.
            if t is None or not t.is_alive():
                self._fail_all(RuntimeError("decode engine closed"))

    def _fail_all(self, err: BaseException) -> None:
        """Fail every in-flight group (resident and queued) and free
        their slots — shutdown, or last-resort cleanup when a tick
        crashes outside the device-call try blocks that attribute
        errors to their own group."""
        for slot, stream in list(self._resident.items()):
            stream.group.fail(err)
            self._record_history(stream.group)
            try:
                self.slots.release(slot)
            except ValueError:
                pass
        self._resident.clear()
        while True:
            stream = self.queue.pop_head()
            if stream is None:
                break
            self._release_stream_kv(stream)
            stream.group.fail(err)
            self._record_history(stream.group)

    def _loop(self) -> None:
        while not self._stop:
            try:
                if self.faults is not None:
                    # Injected whole-engine death: raised HERE, past
                    # tick's containment, so it exercises exactly the
                    # supervised-restart path a real scheduling-layer
                    # crash takes.
                    self.faults.check("engine_death")
                worked = self.tick()
            except BaseException as e:
                # Device errors inside prefill/admit/decode already
                # failed their own group; anything landing here is a
                # whole-engine crash with no owner.  SUPERVISED
                # engines (recovery.EngineSupervisor) recover: the
                # supervisor requeues every stream for
                # token-identical resume, rebuilds the pools, and
                # starts a replacement loop thread — this thread
                # just exits.  Unsupervised (library) engines keep
                # the legacy crash-never-hang behavior: surface the
                # error and fail everything in flight, since
                # retrying the same tick at 20 Hz would spin forever
                # while the stuck groups' clients hang.
                if self.supervisor is not None \
                        and self.supervisor.handle_crash(e):
                    return
                traceback.print_exc(file=sys.stderr)
                self._fail_all(
                    RuntimeError(f"decode engine error: "
                                 f"{type(e).__name__}: {e}"))
                worked = False
            if worked and self.supervisor is not None:
                self.supervisor.note_progress()
            if not worked:
                with self._wake:
                    if self._stop:
                        break
                    self._wake.wait(timeout=0.05)
        # Shutdown drain on the loop thread itself, where touching
        # _resident and the slot free-list can never race a tick.
        self._fail_all(RuntimeError("decode engine closed"))

    def _restart_loop(self) -> bool:
        """Start a REPLACEMENT loop thread after supervised crash
        recovery (called by the supervisor ON the dying loop thread,
        which exits right after).  Returns False when the engine was
        closed mid-recovery — the caller fails the queue instead of
        restarting."""
        with self._thread_lock:
            if self._stop:
                return False
            self._thread = threading.Thread(
                target=self._loop, name="decode-engine",
                daemon=True)
            self._thread.start()
            return True

    # -- one scheduling round -------------------------------------------

    def tick(self) -> bool:
        """One step boundary: deliver pending lifecycle events
        (cancellations, expired deadlines, queue-deadline sheds),
        preempt a batch resident if the interactive TTFT SLO demands
        it, admit/prefill within the policy budget, then one decode
        step over the resident batch.  Returns whether any work was
        done.  Single-threaded by contract (loop thread, or tests
        driving it manually)."""
        worked = self._sweep_lifecycle()
        if self._maybe_preempt():
            worked = True
        budget = self.policy.prefill_budget(bool(self._resident),
                                            self.slots.free_slots)
        while budget > 0:
            stream = self._queue_head()
            if stream is None:
                break
            if stream.group.error is not None:
                self.queue.drop_group(stream.group)
                continue
            if stream.pf_done and not self._can_admit_stream(stream):
                # Prefilled, waiting on a slot / pages: stamp the
                # wait start into its causal timeline (once).
                self._note_blocked(stream)
                break
            self._advance_prefill(stream)
            worked = True
            budget -= 1
        if self._resident:
            self._decode_step()
            worked = True
        # Step-boundary bookkeeping for the debuggability layer: the
        # watchdog's progress signal and the published /debug/state
        # snapshot (throttled to board_interval_s) — host-side only,
        # never under the device lock.  The progress stamp is
        # PROGRESS-gated: a no-op tick (queue nonempty but nothing
        # admittable, no residents) must let staleness grow, or a
        # livelocked-but-spinning loop could never be declared
        # stalled — "the loop thread is alive" is not "the engine is
        # making progress".
        now = time.perf_counter()
        if worked:
            self.last_boundary_t = now
        if now - self._board_t >= self.board_interval_s:
            self._board_t = now
            self.debug_board.publish(self.build_debug_snapshot())
        return worked

    # -- paged-KV accounting ---------------------------------------------

    def _kv_tokens_needed(self, p_len: int, new: int) -> int:
        """A stream's FULL KV reservation: prompt + budget, plus the
        speculative write slack every paged co-tenant of a
        spec-capable pool must leave (a spec round's verify chunk
        writes up to spec_k_cap positions past the last committed
        token, for every resident)."""
        slack = self.policy.spec_k_cap \
            if self.draft_model is not None else 0
        return p_len + new + slack

    def _validate_shared_epoch(self, stream: Stream) -> None:
        """Drop shared prefix pins taken under a page-pool generation
        that crash recovery has since rebuilt: the ids mean nothing
        in the fresh accounting (never unpin them into it), and the
        stream's own materialized prefill makes admission without
        the sharing token-identical — the share is an optimization.
        Runs on the engine thread (the only thread recovery
        alternates with), so the check-then-use is race-free."""
        if stream.kv_shared and stream.kv_epoch is not None \
                and stream.kv_epoch != getattr(self.slots, "epoch",
                                               None):
            stream.kv_shared = None
            stream.kv_epoch = None

    def _kv_admit_tokens(self, stream: Stream) -> int:
        """The token span admission must have pages for: the full
        budget (default reservation discipline), or — lazy — the
        stream's current committed length plus one dispatch span
        (serving/paged.py admit_tokens; the rest grows at step
        boundaries)."""
        need = self._kv_tokens_needed(stream.p_len, stream.new)
        if getattr(self.slots, "lazy", False):
            return self.slots.admit_tokens(
                stream.p_len + max(1, len(stream.out)), need)
        return need

    def _stream_barred(self, stream: Stream) -> bool:
        """Lazy-KV livelock guard: an exhaustion evictee is NOT
        admissible while the stream it was evicted for still waits
        for the freed capacity.  ``Stream.evicted_for`` is set by
        _ensure_lazy_growth and cleared the moment a growth pass
        completes (the beneficiary got its pages), so the bar
        normally lasts exactly one boundary — long enough that the
        T+1 admission (which runs BEFORE the T+1 growth) cannot hand
        the freed pages back to the very stream whose eviction freed
        them.  Also cleared when the beneficiary goes terminal, and
        when the pool has NO residents (no growth can be pending
        without a resident, so a lingering bar would deadlock an
        idle engine).  Engine thread only."""
        b = stream.evicted_for
        if b is None:
            return False
        if b.group.event.is_set() or not self._resident:
            stream.evicted_for = None
            return False
        return True

    def _queue_head(self) -> Optional[Stream]:
        """Admission head: the class-aware queue head, SKIPPING
        streams under an active exhaustion bar — a barred evictee
        (possibly of a higher class) must never head-of-line-block
        the stream it was evicted for."""
        head = self.queue.head()
        if head is None or not self._stream_barred(head):
            return head
        for s in self.queue.snapshot():
            if not self._stream_barred(s):
                return s
        return None

    def _admissible_now(self, stream: Stream) -> bool:
        """Pure check (no reclaim side effects — _pick_window calls
        this every boundary): a free slot AND, paged, enough free
        pages for the stream's reservation net of its shared prefix
        pages (and, lazy, no active exhaustion bar)."""
        self._validate_shared_epoch(stream)
        if self._stream_barred(stream):
            return False
        if self.slots.free_slots == 0:
            return False
        if not self.paged:
            return True
        return self.slots.can_admit(
            self._kv_admit_tokens(stream),
            len(stream.kv_shared or ()))

    def _can_admit_stream(self, stream: Stream) -> bool:
        """Admission gate: a free slot AND (paged) enough free pages
        for the stream's full reservation net of its shared prefix
        pages.  When pages are the blocker, ask the owner's reclaim
        hook (prefix-cache LRU eviction) to free some before giving
        up until the next boundary — stored-but-idle prefixes must
        never starve live traffic."""
        if self._admissible_now(stream):
            return True
        if self._stream_barred(stream) or self.slots.free_slots == 0 \
                or not self.paged:
            return False
        need = self._kv_admit_tokens(stream)
        n_shared = len(stream.kv_shared or ())
        if self.page_reclaim is not None:
            # The hook's contract is "make this many pages FREE" (it
            # evicts until the free count reaches the target), so it
            # gets the stream's whole page need — passing only the
            # deficit would stop short and leave admission blocked
            # at every subsequent boundary.
            try:
                self.page_reclaim(
                    self.slots.pages_needed(need) - n_shared)
            except Exception:
                import logging

                logging.getLogger(__name__).debug(
                    "page_reclaim hook failed", exc_info=True)
            ok = self.slots.can_admit(need, n_shared)
            if ok:
                # The unblock came from evicting stored-but-idle
                # prefix entries, not a co-tenant's eviction.
                self._last_page_free = (None, "prefix_reclaim")
            return ok
        return False

    def _release_stream_kv(self, stream: Stream) -> None:
        """Release a stream's still-PINNED shared prefix pages (set
        at submit, consumed at admission) — called on every terminal
        path that can fire before the pins transfer into a slot
        table."""
        ids = stream.kv_shared
        if ids:
            stream.kv_shared = None
            try:
                # Epoch-guarded: pins from a pool generation that
                # crash recovery rebuilt are dropped by reference.
                self.slots.unpin(ids, epoch=stream.kv_epoch)
            except Exception:
                import logging

                logging.getLogger(__name__).debug(
                    "shared-page release failed", exc_info=True)

    # -- debuggability: block/unblock attribution ------------------------

    def _note_blocked(self, stream: Stream) -> None:
        """First boundary a fully-prefilled head could not admit:
        open its wait in the causal timeline, saying WHAT it waits on
        (a slot, or — paged with a free slot — pages).  One instant
        per blocked episode; the matching ``admit_unblocked`` closes
        it with the wait length and what freed the capacity."""
        if stream.blocked_t is not None:
            return
        now = time.perf_counter()
        stream.blocked_t = now
        args: Dict[str, Any] = {"on": "slot"}
        if self.paged and self.slots.free_slots > 0:
            args["on"] = "kv_pages"
            args["pages_free"] = self.slots.free_page_count()
            args["pages_needed"] = self.slots.pages_needed(
                self._kv_admit_tokens(stream)) \
                - len(stream.kv_shared or ())
        self._emit_instant(stream, "admit_blocked", now,
                           row=stream.row, **args)

    def _note_freed(self, stream: Stream, why: str) -> None:
        """Remember who last freed slot/page capacity — the
        attribution a blocked stream's ``admit_unblocked`` instant
        carries ("which eviction unblocked me")."""
        self._last_page_free = (stream.group.rid, why)

    # -- lifecycle: cancel / deadline / shed / preempt -------------------

    def _sweep_lifecycle(self) -> bool:
        """Deliver, at this step boundary, every pending cancel and
        expired deadline (resident AND queued streams — a cancelled
        request frees its slot within ONE boundary, pinned in
        tests/test_lifecycle.py), and shed queued requests that blew
        their class queue deadline before getting any engine
        attention.  Host-side wall-clock only: deadline math never
        enters a compiled step program (JIT-DEADLINE).

        Fast path: with no cancel pending, no deadline ever armed,
        and no class queue deadline configured, there is nothing the
        scan could find — skip the O(resident + queue) walk (and its
        queue-lock snapshot) on this boundary entirely."""
        if not (self._cancel_pending or self._deadline_armed
                or self.policy.queue_deadline_s is not None
                or self.policy.batch_queue_deadline_s is not None):
            return False
        self._cancel_pending = False
        now = time.perf_counter()
        handled = set()          # id(group) -> already terminated
        worked = False
        for stream in ([s for s in self._resident.values()]
                       + self.queue.snapshot()):
            group = stream.group
            if id(group) in handled or group.error is not None:
                continue
            err = group.cancel_error
            if err is None and group.deadline is not None \
                    and now > group.deadline:
                err = DeadlineExceeded(
                    f"deadline exceeded after "
                    f"{now - group.t_submit:.3f}s "
                    f"({group.status_phase()})")
                group.request_cancel(err)
            if err is None and group.t_first_prefill is None \
                    and stream.slot is None:
                # Zero engine attention so far: the class queue
                # deadline decides whether it may keep waiting.
                qd = self.policy.class_queue_deadline(group.priority)
                if qd is not None and now - group.t_submit > qd:
                    err = ShedError(
                        f"{group.priority} request queued "
                        f"{now - group.t_submit:.3f}s without "
                        f"starting (class queue deadline {qd}s); "
                        f"shed unstarted", reason="queue_deadline",
                        retry_after=self.policy.retry_after_s)
                    group.request_cancel(err)
            if err is not None:
                handled.add(id(group))
                self._cancel_group(group, err, now)
                worked = True
        return worked

    def _cancel_group(self, group: RequestGroup, err: BaseException,
                      now: float) -> None:
        """Terminate ``group`` with lifecycle error ``err``: drop its
        queued streams, evict its residents (slots free THIS
        boundary), emit the terminal span, bump the right counter,
        and wake the waiter."""
        status = terminal_status(err)
        self.queue.drop_group(group)
        for slot, stream in list(self._resident.items()):
            if stream.group is not group:
                continue
            del self._resident[slot]
            self.slots.release(slot)
            self.evicted_total += 1
            self._note_freed(stream, status)
            # Close the decode span at the eviction boundary so the
            # trace shows exactly how much work the cancel discarded.
            self._emit(stream, "decode", stream.t_admit, now,
                       row=stream.row, slot=slot,
                       tokens=len(stream.out), terminal=status)
            stream.slot = None
        for stream in group.streams:
            self._release_stream_kv(stream)
            self._emit_instant(stream, status, now, row=stream.row,
                               tokens=len(stream.out))
        if isinstance(err, ShedError):
            with self._shed_lock:   # submit's draining gate races us
                self.shed_total += 1
                self.shed_by_class[group.priority] += 1
        elif isinstance(err, DeadlineExceeded):
            self.expired_total += 1
        else:
            self.cancelled_total += 1
        group.fail(err)
        self._record_history(group)

    def _recent_ttft_p99(self) -> Optional[float]:
        """p99 of the sliding interactive-TTFT window (None until
        there are observations) — the degraded-class half of the
        preemption trigger."""
        if not self._ttft_recent:
            return None
        xs = sorted(self._ttft_recent)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def _maybe_preempt(self) -> bool:
        """Preempt ONE batch resident when the interactive class
        needs its slot: the head of the interactive queue is
        admit-ready (fully prefilled) with no free slot, and the
        interactive admission-anchored TTFT — the p99 of the PR 4
        histogram, or this head's own wait — has degraded past the
        ``slo_ttft_s`` target.  The victim (the batch resident with
        the most remaining budget, i.e. the longest expected hold) is
        evicted through the same path as cancellation and REQUEUED at
        the front of the batch class with its generated-so-far
        prefix: resumption is token-identical (Stream.prepare_resume)
        so preemption costs re-prefill, never correctness."""
        slo = self.policy.slo_ttft_s
        if slo is None or self.slots.free_slots > 0:
            return False
        head = self._queue_head()   # bar-aware: never preempt FOR a
        #                             barred exhaustion evictee
        if head is None or head.group.priority != "interactive" \
                or not head.pf_done:
            return False
        now = time.perf_counter()
        waited = now - head.group.t_submit
        # The control-law reason rides the victim's ``preempted``
        # instant (and so its history record): which trigger fired.
        reason = "head_wait_over_half_slo"
        if waited <= slo / 2:
            # Head-wait trigger acts at HALF the budget: preempting
            # only once the target is already blown would guarantee
            # a TTFT past the SLO by the time the admission it buys
            # lands — a controller has to act with margin.  Under
            # half-budget, consult the class p99 over the RECENT
            # window (self._ttft_recent — same observations the
            # exported ttft_interactive histogram records, but
            # sliding, so a transient bad period stops arming
            # preemption once healthy TTFTs wash it out instead of
            # latching until restart).
            p99 = self._recent_ttft_p99()
            if p99 is None or p99 <= slo:
                return False
            reason = "ttft_p99_degraded"
        victim = None
        for slot, stream in self._resident.items():
            if stream.group.priority != "batch":
                continue
            rem = stream.new - len(stream.out)
            if victim is None or rem > victim[2]:
                victim = (slot, stream, rem)
        if victim is None:
            return False        # all residents interactive: defer only
        slot, stream, _ = victim
        self.preempted_total += 1
        stream.preempts += 1
        # The causal evidence a co-tenancy incident needs: WHO forced
        # this eviction (the preemptor's request ID) and WHY the
        # control law fired.
        self._evict_requeue(slot, stream, "preempted", now,
                            by=head.group.rid, reason=reason,
                            head_waited_ms=round(1e3 * waited, 3))
        return True

    def _evict_requeue(self, slot: int, stream: Stream, why: str,
                       now: float, *, release: bool = True,
                       front: bool = True,
                       **instant_args) -> None:
        """Evict a RESIDENT stream and requeue it for token-identical
        resume — the one path every requeue flavor (SLO preemption,
        quarantine bisection, crash recovery, lazy-KV exhaustion)
        shares, because the safety argument is one argument: resume
        re-prefills ``prompt ++ out[:-1]`` in pow2 pieces (bounded
        program set, steady-state quiet) and re-enters feeding
        ``out[-1]`` with ``next_index == len(out)``, so no token is
        ever resampled (Stream.prepare_resume).

        ``front=True`` (every flavor but exhaustion) requeues at the
        head of the stream's class; exhaustion evictions requeue at
        the BACK (``front=False``) — the freed pages belong to the
        growth-blocked beneficiary and everyone already queued, not
        to the evictee (AdmissionQueue.requeue_back).

        ``release=False`` skips the slot release for crash recovery,
        whose wholesale pool rebuild (slots.reset) makes per-slot
        release both redundant and — paged — unsafe (the page
        accounting it would touch is about to be reset)."""
        del self._resident[slot]
        if release:
            self.slots.release(slot)
        self.evicted_total += 1
        self._note_freed(stream, why)
        self._emit(stream, "decode", stream.t_admit, now,
                   row=stream.row, slot=slot, tokens=len(stream.out),
                   terminal=why)
        self._emit_instant(stream, why, now, row=stream.row,
                           slot=slot, tokens=len(stream.out),
                           **instant_args)
        stream.slot = None
        # pow2 pieces, not chunk_plan: the resume length is
        # data-dependent (prompt + commits at the eviction point),
        # so one-piece prefill would be a fresh compile per
        # eviction — pow2 decomposition keeps the resume program
        # set bounded and steady-state quiet.
        stream.prepare_resume(SchedulerPolicy.pow2_pieces(
            stream.p_len + len(stream.out) - 1))
        if front:
            self.queue.requeue_front(stream)
        else:
            self.queue.requeue_back(stream)
        self.requests_requeued_total += 1

    def mean_resident_position(self) -> float:
        """Mean absolute decode position over resident slots (0.0
        when the pool is empty) — the flight recorder's context-
        length input to the per-token attention-flop term.  Engine
        thread only (it reads the slot arrays the tick mutates)."""
        if not self._resident:
            return 0.0
        return float(np.mean([self.slots.positions[s]
                              for s in self._resident]))

    def run_until_idle(self, max_ticks: int = 100000) -> None:
        """Drain queue + slots synchronously (tests/offline use)."""
        for _ in range(max_ticks):
            if not self.tick():
                return
        raise RuntimeError("engine did not go idle within max_ticks")

    # -- crash recovery (recovery.EngineSupervisor) ----------------------

    def recover_from_crash(self) -> int:
        """The engine half of supervised crash recovery — "requeue
        everything and replay" (VirtualFlow's decoupling of request
        state from the device holding it, arXiv:2009.09523).  Called
        by the supervisor with NO loop thread running, so touching
        loop-thread state is race-free by construction.  Returns the
        number of resident streams requeued.

        - Every RESIDENT stream is requeued through the preempt-
          resume path: its committed tokens are host-side state, so
          resumption is token-identical per seed however the engine
          died (pinned in tests/test_faults.py).
        - Every PARTIAL PREFILL (and stored-prefix seed) is reset to
          re-prefill from its tokens — the partial cache referenced
          a device state the crash made untrustworthy; chunked
          prefill is position-keyed, so a from-scratch refill equals
          the interrupted one.  pow2 pieces keep the replay program
          set bounded (zero steady-state recompiles after recovery,
          pinned).
        - The slot/page pools rebuild IN PLACE (``slots.reset``):
          fresh storage, SAME compiled step/insert programs.
        - Stale shared-page pins are dropped by reference (never
          unpinned INTO the fresh pool — its accounting starts
          all-free); the owner's recovery hook flushes the prefix
          store whose payloads those pins protected."""
        now = time.perf_counter()
        # Quarantine suspicion dies with the loop that formed it:
        # the fault context behind a pre-crash episode is gone, and
        # a stale suspect re-admitted alone must not be convictable
        # without fresh bisection evidence.  (The conviction-streak
        # counter deliberately SURVIVES recovery — a fault that
        # recurs after restart escalates immediately instead of
        # convicting more innocents; any successful dispatch resets
        # it.)
        self._suspects.clear()
        # Exhaustion bars die with the pool generation: the rebuilt
        # all-free pool has no pending growth to protect.
        self._exhaust_bars.clear()
        n = 0
        for slot, stream in sorted(list(self._resident.items())):
            self._evict_requeue(slot, stream, "crash_requeued", now,
                                release=False)
            n += 1
        for stream in self.queue.snapshot():
            stream.kv_shared = None
            stream.evicted_for = None
            if stream.filled or stream.cache is not None \
                    or stream.pf_done:
                stream.pieces = SchedulerPolicy.pow2_pieces(
                    stream.pf_toks.shape[1])
                stream.filled = 0
                stream.cache = None
                stream.d_cache = None
                stream.logits = None
                stream.pf_done = False
                stream.blocked_t = None
        with self.device_lock:
            # Under the device lock: handler threads scatter/gather
            # prefix pages under this same lock, and their
            # in-device-lock epoch checks are only airtight if the
            # rebuild (which bumps the epoch) cannot interleave.
            # Pure host work — the hold is microseconds.
            self.slots.reset()
        self._last_page_free = None
        self.last_boundary_t = time.perf_counter()
        return n

    # -- telemetry ------------------------------------------------------

    def _emit(self, stream: Stream, name: str, t0: float, t1: float,
              **args) -> None:
        """One lifecycle span for ``stream``: into the shared trace
        ring, and (when a ``timings`` block or the history ring wants
        it) onto the stream's own event list.  Every span carries the
        request ID — the correlation key ``trace_report.py
        --request`` and the /requests records filter on.

        CONTAINED: a telemetry failure (injected via the
        ``telemetry`` fault site, or a real bug in the ring) is
        counted and dropped, never propagated — observability must
        stay strictly isolated from the request path (the
        degradation ladder, docs/SERVING.md)."""
        if stream.group.rid is not None:
            args.setdefault("rid", stream.group.rid)
        try:
            if self.faults is not None:
                self.faults.check("telemetry")
            self.tel.span(stream.sid or 0, name, t0, t1, **args)
        except Exception:
            # ptpu: lockfree[best-effort drop counter: a lost increment under-counts a diagnostic, nothing else]
            self.telemetry_errors_total += 1
        if stream.events is not None:
            stream.events.append((name, t0, t1, args))

    def _emit_instant(self, stream: Stream, name: str, t: float,
                      **args) -> None:
        if stream.group.rid is not None:
            args.setdefault("rid", stream.group.rid)
        try:
            if self.faults is not None:
                self.faults.check("telemetry")
            self.tel.instant(stream.sid or 0, name, t, **args)
        except Exception:
            self.telemetry_errors_total += 1
        if stream.events is not None:
            stream.events.append((name, t, t, args))

    # -- prefill + admission --------------------------------------------

    def _pf_fn(self, s_len: int, first: bool):
        """Jitted prefill (fresh cache) / extend (append at position)
        program for one piece length — the engine-side twin of the
        server's prefix-cache split programs."""
        import jax

        from ..models import generate as G

        if self._prefill_fns is not None:
            return self._prefill_fns(s_len, first)
        model, variables = self.model, self.variables

        def build():
            if first:
                return jax.jit(
                    lambda toks: G.prefill(model, variables, toks))
            return jax.jit(lambda cache, toks, pos: G.prefill(
                model, variables, toks, cache=cache, position=pos))

        return lru_get(self._pf_fns,
                       ("pfill" if first else "extend", s_len),
                       self._pf_cap, build,
                       sentinel=self.sentinel, kind="engine_prefill")

    def _pf_fn_draft(self, s_len: int, first: bool):
        """Draft-model twin of :meth:`_pf_fn` for speculative
        streams' draft prefill."""
        import jax

        from ..models import generate as G

        draft, dvars = self.draft_model, self.draft_variables

        def build():
            if first:
                return jax.jit(
                    lambda toks: G.prefill(draft, dvars, toks))
            return jax.jit(lambda cache, toks, pos: G.prefill(
                draft, dvars, toks, cache=cache, position=pos))

        return lru_get(self._pf_fns_draft,
                       ("pfill" if first else "extend", s_len),
                       self._pf_cap, build,
                       sentinel=self.sentinel, kind="draft_prefill")

    def _advance_prefill(self, stream: Stream) -> None:
        """Run ONE prefill piece for the head-of-queue stream; admit it
        into a slot when the prompt is fully consumed AND a slot is
        free (prefill works AHEAD while all slots are busy, so a
        freshly evicted slot admits an already-prefilled request the
        same boundary).  Chunked prefill is position-keyed cache
        extension (models/generate._prefill): piecewise equals
        one-shot, so interleaving changes latency, never tokens."""
        import jax

        group = stream.group
        if stream.t_prefill_start is None:
            stream.t_prefill_start = time.perf_counter()
            if group.t_first_prefill is None:
                group.t_first_prefill = stream.t_prefill_start
            # Queue span closes the moment the stream first gets
            # engine attention (prefill, or straight admission for
            # full-length prefix hits).
            self._emit(stream, "queue", group.t_submit,
                       stream.t_prefill_start, row=stream.row)
        if stream.pieces:               # full-length prefix hits skip
            piece = stream.pieces[0]
            # pf_toks, not toks: a PREEMPTED stream re-prefills
            # prompt ++ committed[:-1] (Stream.prepare_resume) so its
            # resumption is token-identical; for everyone else the
            # two are the same array.
            toks = stream.pf_toks[:, stream.filled:stream.filled
                                  + piece]
            spec = stream.sampling.spec_k > 0
            t_piece = time.perf_counter()
            try:
                with self.device_lock, self._exact():
                    if stream.cache is None:
                        logits, cache = self._pf_fn(piece, True)(toks)
                    else:
                        logits, cache = self._pf_fn(piece, False)(
                            stream.cache, toks, stream.filled)
                    if spec:
                        # Speculative streams prefill the DRAFT model
                        # too (same pieces — the chunked-prefill
                        # exactness contract holds per model).
                        if stream.d_cache is None:
                            _, d_cache = self._pf_fn_draft(
                                piece, True)(toks)
                        else:
                            _, d_cache = self._pf_fn_draft(
                                piece, False)(stream.d_cache, toks,
                                              stream.filled)
                        stream.d_cache = d_cache
                    jax.block_until_ready(logits)
            except BaseException as e:
                self._fail_group(group, e)
                return
            stream.cache = cache
            stream.logits = logits
            stream.filled += piece
            stream.pieces.pop(0)
            self.prefill_chunks_total += 1
            self._emit(stream, "prefill", t_piece,
                       time.perf_counter(), row=stream.row,
                       piece=piece, filled=stream.filled)
            if stream.pieces:
                return                  # more prompt to consume
        if not stream.pf_done:
            stream.pf_done = True
            # Never on a resumed stream: its pf_toks mix generated
            # tokens into the prefill, which must not be stored back
            # as a prompt prefix.
            if group.on_prefilled is not None and not stream.resume:
                try:
                    group.on_prefilled(stream)
                except Exception:
                    # Cache store-back must not fail the request, but
                    # a broken prefix cache should be diagnosable.
                    import logging

                    logging.getLogger(__name__).debug(
                        "on_prefilled hook failed", exc_info=True)
        if not self._can_admit_stream(stream):
            return          # wait, fully prefilled, for slot/pages
        # Pop THIS stream, never "the head": a concurrent interactive
        # submit can change the class-aware head between the tick's
        # head() and this pop (scheduler.AdmissionQueue.pop_stream).
        self.queue.pop_stream(stream)
        self._admit(stream)

    def _first_token(self, stream: Stream, logits: np.ndarray) -> int:
        """Token 0 for an admitted stream, from the prefill logits.
        Greedy: host argmax (np and jnp agree on first-max
        tie-breaking).  Sampled: the SAME position-keyed sampler the
        slot step program runs, at token index 0, with the stream's
        fold_in(PRNGKey(seed), row) base key — jitted once so
        admission stays cheap."""
        import jax

        spec = stream.sampling
        if not spec.sampled:
            return int(np.argmax(logits))
        from ..models import generate as G

        if stream.base_key is None:
            # device_get, not bare np.asarray: the sync is 8 bytes
            # and intentional — spell it so (HOST-SYNC).
            stream.base_key = np.asarray(jax.device_get(
                jax.random.fold_in(jax.random.PRNGKey(spec.seed),
                                   stream.row)))
        if self._admit_sample_fn is None:
            self.sentinel.miss("admit_sample")
            self._admit_sample_fn = jax.jit(
                lambda l, k, t, tk, tp:
                G._sample_positional_row(l, k, 0, t, tk, tp))
        with self.device_lock:
            return int(self._admit_sample_fn(
                logits, stream.base_key,
                np.float32(spec.temperature), np.int32(spec.top_k),
                np.float32(spec.top_p)))

    def _admit(self, stream: Stream) -> None:
        """Step-boundary admission: first token from the prefill
        logits (argmax, or the position-keyed sampler for sampled
        streams), cache into a free slot.  Device failures
        (including the FIRST insert's lazy stacked-pool allocation —
        the engine's largest device buy) release the slot and fail
        the group: a waiter must never hang on an admission that
        silently died.

        A RESUMED (preempted) stream skips token sampling entirely —
        all its committed tokens already exist — and re-enters its
        slot feeding ``out[-1]`` at its original position with
        ``next_index == len(out)``, so the next draw uses exactly the
        position key the uninterrupted run would have."""
        import jax

        slot = self.slots.acquire()
        assert slot is not None, "admission without a free slot"
        stream.last_slot = slot
        stream.evicted_for = None    # an admitted stream carries no
        #                              exhaustion bar
        spec = stream.sampling
        resumed = stream.resume
        if not resumed:
            try:
                logits = np.asarray(jax.device_get(stream.logits))[0]
                first = self._first_token(stream, logits)
            except BaseException as e:
                self.slots.release(slot)
                self._fail_group(stream.group, e)
                return
            stream.out.append(first)
        stream.t_admit = time.perf_counter()
        stream.group.t_last_admit = stream.t_admit
        if stream.group.t_first_admit is None:
            # First token of the whole request exists NOW (sampled
            # from the prefill logits) — the TTFT anchor, observed
            # into the request's CLASS histogram (the preemption
            # control signal, docs/SERVING.md).
            stream.group.t_first_admit = stream.t_admit
            ttft = stream.t_admit - stream.group.t_submit
            self.tel.observe("ttft_" + stream.group.priority, ttft,
                             exemplar=stream.group.rid)
            if stream.group.priority == "interactive":
                self._ttft_recent.append(ttft)
        self._emit_instant(stream, "admit", stream.t_admit,
                           row=stream.row, slot=slot,
                           **({"resumed": True} if resumed else {}))
        if stream.blocked_t is not None:
            # Close the admission wait opened by _note_blocked, with
            # the attribution: whose eviction freed the capacity.
            unb = self._last_page_free
            self._emit_instant(
                stream, "admit_unblocked", stream.t_admit,
                row=stream.row, slot=slot,
                wait_ms=round(
                    1e3 * (stream.t_admit - stream.blocked_t), 3),
                **({"unblocked_by": unb[0], "freed_via": unb[1]}
                   if unb is not None else {}))
            stream.blocked_t = None
        stream.logits = None
        if not resumed and stream.done():   # new == 1, or instant eos
            stream.cache = None
            stream.d_cache = None
            self._release_stream_kv(stream)  # never mapped a table
            self.slots.release(slot)
            stream.slot = slot          # zero-length decode span
            self._complete(stream)      # still keys the slot id
            stream.slot = None
            self._count_admitted(spec, stream.group.priority)
            self.evicted_total += 1
            return
        if (spec.speculative or (resumed and spec.sampled)) \
                and stream.base_key is None:
            # Greedy speculative streams never drew token 0 from the
            # PRNG, but the spec step program still wants the slot's
            # base key operand (the sampled lanes are dead at
            # temperature 0 — zeros would work — yet arming the real
            # key keeps one invariant: every speculative slot's key
            # is fold_in(PRNGKey(seed), row)).  A CROSS-REPLICA
            # resumed sampled stream (submit resume_tokens=) skipped
            # _first_token on THIS engine entirely — its token 0 was
            # drawn by the prior attempt — so the key is armed here:
            # same fold_in, pure function of the request.
            stream.base_key = np.asarray(jax.device_get(
                jax.random.fold_in(jax.random.PRNGKey(spec.seed),
                                   stream.row)))
        kw = {}
        if self.paged:
            # Ownership of the pinned shared pages passes to insert
            # (it unpins on its own failure paths), so clear the
            # stream's reference FIRST — a later terminal path must
            # not double-release.
            shared = stream.kv_shared or ()
            stream.kv_shared = None
            kw = dict(total_tokens=self._kv_tokens_needed(
                stream.p_len, stream.new), shared_pages=shared)
        try:
            if self.faults is not None:
                # Injected page-pool allocation failure: raises a
                # PageExhausted subclass, so it rides the SAME
                # transient-shortage requeue below that a real
                # admission-gate race takes.
                self.faults.check("page_alloc")
            with self.device_lock:
                # Uniform across fresh and resumed admissions: feed
                # the LAST committed token at its absolute position
                # (fresh: token 0 at p_len), and draw token
                # ``len(out)`` next.
                self.slots.insert(
                    slot, stream.cache, stream.out[-1],
                    stream.p_len + len(stream.out) - 1,
                    base_key=stream.base_key,
                    next_index=len(stream.out),
                    temperature=spec.temperature, top_k=spec.top_k,
                    top_p=spec.top_p, draft_cache=stream.d_cache,
                    spec_k=spec.spec_k, **kw)
        except PageExhausted as pe:
            # A handler thread (prefix store) reserved pages between
            # the admission gate and this insert: a TRANSIENT
            # shortage, not a request failure — put the stream back
            # at the front of its class through the preempt-resume
            # machinery (insert already released its pins/pages), so
            # it re-prefills and admits when pages free.  The
            # fits-but-not-now contract: wait, never 500.
            self.slots.release(slot)
            if kw.get("shared_pages") and getattr(pe, "injected",
                                                  False):
                # An INJECTED exhaustion fires at the probe, BEFORE
                # insert (the pin owner on real failures) ever ran —
                # the transferred pins must be released here or the
                # chaos harness leaks the very pages whose
                # accounting it exists to attest.
                try:
                    self.slots.unpin(kw["shared_pages"],
                                     epoch=stream.kv_epoch)
                except Exception:
                    import logging

                    logging.getLogger(__name__).debug(
                        "injected-fault pin release failed",
                        exc_info=True)
                stream.kv_epoch = None
            self._emit_instant(stream, "page_requeued",
                               time.perf_counter(), row=stream.row,
                               tokens=len(stream.out))
            stream.prepare_resume(SchedulerPolicy.pow2_pieces(
                stream.p_len + len(stream.out) - 1))
            self.queue.requeue_front(stream)
            self.requests_requeued_total += 1
            return
        except BaseException as e:
            self.slots.release(slot)
            self._fail_group(stream.group, e)
            return
        stream.cache = None             # pool owns the KV now
        stream.d_cache = None
        stream.slot = slot
        self._resident[slot] = stream
        if resumed:
            stream.resume = False
            stream.resumes += 1
            self.resumed_total += 1
        else:
            self._count_admitted(spec, stream.group.priority)

    def _count_admitted(self, spec: SamplingSpec,
                        priority: str) -> None:
        self.admitted_total += 1
        self.admitted_by_class[priority] += 1
        if spec.speculative:
            self.admitted_spec_total += 1
        elif spec.sampled:
            self.admitted_sampled_total += 1
        else:
            self.admitted_greedy_total += 1

    # -- decode ---------------------------------------------------------

    def _pick_window(self) -> int:
        """Decode steps to fuse into the next device dispatch.

        Window = 1 whenever a smaller granularity could make forward
        progress sooner: a queued request with a free slot is
        admissible at the very next boundary, an eos-capable resident
        might free one at any step, and a queued prompt still mid-
        prefill earns one chunk per BOUNDARY (prefill_budget) — fusing
        would starve its prefill-ahead and leave the next evicted slot
        waiting on an unfinished prompt.  Otherwise the only capacity
        event is a BUDGET eviction, and ``min(remaining)`` lands the
        window end exactly on the earliest one — so fusing up to
        ``decode_window`` steps (rounded down to a power of two to
        bound compiled programs) saves per-step dispatch + host-sync
        overhead without delaying a single admission."""
        cap = self.policy.decode_window
        if cap <= 1:
            return 1
        waiters = getattr(self.device_lock, "waiters", None)
        if waiters is not None and waiters():
            # A handler thread is WAITING on the device lock right
            # now (wire-fetch admit, direct /prefill, solo request):
            # fusing would make it wait out the whole fused hold.
            # Window 1 bounds its wait to one step, exactly like a
            # queued interactive head.
            return 1
        head = self.queue.head()
        if head is not None and (
                not head.pf_done
                # Admissible NEXT BOUNDARY — for paged pools a free
                # slot alone is not admissibility: a head blocked on
                # PAGES can't admit until a budget eviction frees
                # some, so fusing toward that eviction loses nothing
                # (an eos-capable resident still pins the window to 1
                # below, since an eos frees pages mid-window).
                or self._admissible_now(head)
                or any(s.eos_id is not None
                       for s in self._resident.values())
                # An armed TTFT SLO makes every boundary a potential
                # preemption point while an interactive request
                # waits: fusing would delay it by the whole window.
                or (self.policy.slo_ttft_s is not None
                    and head.group.priority == "interactive")):
            return 1
        if any(s.group.deadline is not None
               for s in self._resident.values()):
            # Deadlines are delivered at boundaries only; fusing
            # past one would hold a dead request's slot for the
            # window tail.  Cancels can land at any moment, so only
            # actually-armed deadlines (cheap to check) cost fusion.
            return 1
        # Budget horizon in ROUNDS, advance-aware: a speculative slot
        # may commit up to spec_k tokens per round, so fusing
        # ``rem // spec_k`` rounds can never push any slot past its
        # budget (no wasted rounds, and — because a spec round's
        # verify chunk touches up to position + spec_k — no slot ever
        # writes past the capacity the server validated).
        rem = min((s.new - len(s.out)) //
                  (s.sampling.spec_k if s.sampling.speculative else 1)
                  for s in self._resident.values())
        w, cap = 1, min(cap, max(1, rem))
        while w * 2 <= cap:
            w *= 2
        return w

    # -- step-boundary fault containment ---------------------------------

    def _dispatch_step(self, dispatch):
        """Contained step dispatch — the crash-only containment
        ladder (docs/SERVING.md "Fault tolerance").  Returns the
        dispatch result, or None when containment resolved the
        failure by mutating the resident set (quarantine evictions /
        convictions) — the caller skips this boundary's commit and
        the next tick re-plans.

        Classification of a failing dispatch:

        - TRANSIENT (faults.is_transient — injected TransientFault,
          or any error carrying ``ptpu_transient``): retried in
          place under the shared bounded jittered-backoff
          :class:`~polyaxon_tpu.serving.recovery.RetryPolicy`.  A
          retry re-runs the identical dispatch — no tokens were
          committed, and a partially-written cache is rewritten with
          identical values (every step is a pure function of the
          committed prefix) — so retries never change output.
        - POISONED (faults.is_poisoned), or transient with retries
          exhausted, or any other error with residents to protect:
          :meth:`_quarantine_step` — bisect the resident suspects
          until the culprit fails ALONE, requeue everyone else for
          token-identical resume.

        A containment round that cannot converge (a fault tracking
        no single request — e.g. the device itself died) escalates
        by raising: the loop's catch-all hands it to the supervisor
        (restart + requeue) or, unsupervised, fails everything
        visibly.  Either way: bounded, never a hang."""
        attempt = 0
        rounds = 0
        while True:
            rounds += 1
            if rounds > 4 * self.slots.n_slots + 8:
                raise RuntimeError(
                    "step-fault containment did not converge "
                    "(failures outlasted per-request quarantine); "
                    "escalating to engine recovery")
            try:
                if self.faults is not None:
                    # slow_step sleeps OUTSIDE the device lock so an
                    # injected stall wedges the engine loop (what the
                    # stall watchdog watches), not every solo caller.
                    self.faults.check("slow_step")
                    self.faults.check("step", rids=[
                        s.group.rid
                        for s in self._resident.values()])
                out = dispatch()
            except BaseException as e:
                if not self._resident:
                    raise       # nothing to contain: scheduling bug
                if is_transient(e) and not is_poisoned(e) \
                        and attempt < self.retry_policy.max_attempts:
                    delay = self.retry_policy.delay_s(attempt)
                    attempt += 1
                    self.step_retries_total += 1
                    try:
                        self.tel.instant(
                            0, "step_retry", time.perf_counter(),
                            pid=ENGINE_PID, error=type(e).__name__,
                            attempt=attempt,
                            backoff_ms=round(1e3 * delay, 3))
                    except Exception:
                        # Same isolation contract as _emit: a broken
                        # ring must never turn a retryable step
                        # fault into an engine crash.
                        self.telemetry_errors_total += 1
                    time.sleep(delay)
                    continue
                self._quarantine_step(e)
                if not self._resident:
                    return None
                continue
            self._convictions_without_success = 0
            if self._suspects:
                # A successful dispatch exonerates every RESIDENT
                # suspect: the deterministic fault did not fire, so
                # the culprit is not among them.
                for s in self._resident.values():
                    self._suspects.discard(s.group)
            return out

    def _quarantine_step(self, err: BaseException) -> None:
        """One quarantine-bisection round for a poisoned step
        failure: isolate WHICH resident request keeps failing the
        shared dispatch, fail only it, resume everyone else
        token-identically.

        The invariant the machinery rides: a poisoned failure fires
        exactly when its culprit is resident.  So —

        - no resident suspects yet: the failing dispatch implicates
          every resident (fresh episode — mark them all);
        - ONE suspect, and it is the SOLE resident: it just failed
          ALONE — CONVICTED.  It fails with the typed
          :class:`~.scheduler.PoisonedRequest` (500 +
          ``reason: poisoned_request``), and every other suspect is
          exonerated;
        - one suspect among UNMARKED residents (a suspect carried
          over from an earlier episode, sharing the dispatch with
          requests admitted since): the failure implicates everyone
          present — a lone stale suspect must NOT be convicted on
          another request's fault, so every resident is (re)marked
          and bisection continues on fresh evidence;
        - several resident suspects: BISECT — evict half to the
          requeue path (token-identical resume) and let the caller
          re-dispatch with the rest resident.

        A culprit that escapes a bisection round (its half was
        evicted, so the re-dispatch succeeded) stays marked across
        episodes; once bisection leaves it the sole RESIDENT of a
        failing dispatch, it is convicted.  Convergence is bounded
        by the resident count per round (_dispatch_step's round
        guard — and the conviction-streak escalation — handle the
        pathological fault that tracks no request at all)."""
        now = time.perf_counter()
        # Suspects whose group already reached a terminal state
        # (cancelled, expired, completed pre-conviction) leave the
        # pool lazily — the set must stay bounded by live requests.
        for g in [g for g in self._suspects if g.event.is_set()]:
            self._suspects.discard(g)
        suspects = [(slot, s)
                    for slot, s in sorted(self._resident.items())
                    if s.group in self._suspects]
        if not suspects or (len(suspects) == 1
                            and len(self._resident) > 1):
            for s in self._resident.values():
                self._suspects.add(s.group)
            suspects = sorted(self._resident.items())
        if len(suspects) == 1:
            if self._convictions_without_success >= 2:
                # Two convictions with not one working dispatch
                # between them: the failure is not request-tied —
                # convicting a third resident would just 500 another
                # innocent.  Escalate: the raise propagates to the
                # loop's catch-all, where the supervisor restarts
                # the engine (and, if the fault persists, the crash
                # storm trips the breaker into fail-fast shedding).
                raise RuntimeError(
                    "step failures persist across quarantine "
                    "convictions (no successful dispatch between "
                    "episodes) — the fault tracks the engine, not "
                    "a request; escalating to engine recovery"
                ) from err
            slot, stream = suspects[0]
            self._convict(slot, stream, err, now)
            # Culprit found: every other suspect (requeued during
            # bisection) is exonerated.
            self._suspects.clear()
            return
        for slot, stream in suspects[: len(suspects) // 2]:
            self._evict_requeue(slot, stream, "quarantined", now,
                                error=type(err).__name__)

    def _convict(self, slot: int, stream: Stream,
                 err: BaseException, now: float) -> None:
        """Fail the isolated culprit — and ONLY it — with the typed
        PoisonedRequest; its co-tenants keep decoding."""
        group = stream.group
        self.poisoned_total += 1
        self._convictions_without_success += 1
        self._note_freed(stream, "poisoned")
        self._emit(stream, "decode", stream.t_admit, now,
                   row=stream.row, slot=slot, tokens=len(stream.out),
                   terminal="poisoned")
        self._emit_instant(stream, "poisoned", now, row=stream.row,
                           slot=slot, error=type(err).__name__)
        self._fail_group(group, PoisonedRequest(
            f"request {group.rid} poisoned the shared decode step "
            f"and was quarantined (co-tenants resumed unaffected): "
            f"{type(err).__name__}: {err}"))

    def _engine_instant(self, name: str, t: float, **args) -> None:
        """One instant on the ENGINE trace track (growth/preempt
        markers for the trace_report page strip) — same isolation
        contract as _emit: a broken ring is counted, never raised."""
        try:
            self.tel.instant(0, name, t, pid=ENGINE_PID, **args)
        except Exception:
            self.telemetry_errors_total += 1

    def _ensure_lazy_growth(self, span: int) -> bool:
        """LAZY-KV step-boundary growth: before a dispatch that will
        write ``span`` positions per resident slot, make sure every
        resident's page table covers its writes
        (PagedSlotKVManager.grow_slot, capped at each slot's full
        budget).  On POOL EXHAUSTION, preempt the resident with the
        most remaining budget — the longest expected page hold —
        through the shared ``_evict_requeue`` path (token-identical
        resume) and retry, until every survivor can grow.  Returns
        False when the boundary was consumed by evictions (resident
        set mutated or emptied; the next tick re-plans).

        LIVELOCK-FREE by two rules: (1) exhaustion evictees requeue
        at the BACK of their class (never ahead of anything already
        waiting, the blocked beneficiary included), and (2) each
        evictee carries ``evicted_for`` — the growth-blocked stream
        its eviction served — and the admission gate skips it until
        the next EVICTION-FREE growth pass completes, so the freed
        pages cannot be stolen back at the very next boundary's
        admission (which runs before that boundary's growth) by the
        stream whose eviction freed them.  Each failed round evicts exactly one
        resident, so the loop is bounded by the resident count — and
        the submit-time can-never-fit shed guarantees a sole
        resident's growth always fits, so a growth-blocked stream
        eventually wins."""
        evicted_any = False
        while True:
            blocked = None
            for slot, stream in sorted(self._resident.items()):
                budget = self._kv_tokens_needed(stream.p_len,
                                                stream.new)
                need = min(budget,
                           int(self.slots.positions[slot]) + span)
                grown = self.slots.grow_slot(slot, need)
                if grown is None and self.page_reclaim is not None:
                    # STORED-BUT-IDLE prefix pages yield before any
                    # LIVE resident does: ask the owner's reclaim
                    # hook (prefix-store spill/eviction) to free the
                    # blocked growth's deficit, exactly as the
                    # admission gate does — preempting a resident
                    # while reclaimable cache pages sit idle would
                    # invert the tier order (and a SOLE resident
                    # could self-evict into a re-prefill spin).
                    try:
                        self.page_reclaim(
                            self.slots.grow_need(slot, need))
                    except Exception:
                        import logging

                        logging.getLogger(__name__).debug(
                            "page_reclaim hook failed during lazy "
                            "growth", exc_info=True)
                    grown = self.slots.grow_slot(slot, need)
                if grown is None:
                    blocked = (slot, stream)
                    break
                if grown:
                    self._engine_instant(
                        "kv_grow", time.perf_counter(), slot=slot,
                        pages=grown, rid=stream.group.rid)
            if blocked is None:
                # Bars clear only on a pass that succeeded WITHOUT
                # evictions: the pass that evicted must leave its
                # bars standing across the next boundary's ADMISSION
                # (which runs before the next growth), or the freed
                # pages could be handed right back to the evictee.
                if not evicted_any and self._exhaust_bars:
                    for v in self._exhaust_bars:
                        v.evicted_for = None
                    self._exhaust_bars.clear()
                return not evicted_any
            now = time.perf_counter()
            _bslot, bstream = blocked
            victim = None
            for slot, stream in self._resident.items():
                rem = stream.new - len(stream.out)
                if victim is None or rem > victim[2]:
                    victim = (slot, stream, rem)
            slot, stream, _rem = victim
            self.kv_preempt_exhaustion_total += 1
            self.preempted_total += 1
            stream.preempts += 1
            self._engine_instant("kv_preempt", now, slot=slot,
                                 rid=stream.group.rid,
                                 blocked_rid=bstream.group.rid)
            self._evict_requeue(slot, stream, "preempted", now,
                                front=False,
                                reason="kv_pages_exhausted",
                                blocked_rid=bstream.group.rid)
            if stream is not bstream:
                # The victim must not re-admit ahead of the stream
                # it was evicted for (a self-eviction has no
                # beneficiary to bar against).
                stream.evicted_for = bstream
                self._exhaust_bars.append(stream)
            evicted_any = True
            if not self._resident:
                return False

    def _decode_step(self) -> None:
        """Advance every resident stream by one fused window of decode
        steps; evict finished streams so their slots are admissible
        the SAME boundary.  Within a window a stream stops consuming
        at its own eos/budget (each token depends only on its prefix
        and rows never interact, so the window's later tokens for that
        stream are discardable garbage — exactness is untouched)."""
        window = self._pick_window()
        # Program selection is a pool property: any speculative
        # resident switches the pool to the SPEC program (greedy/
        # sampled co-tenants ride its one-token plain lane, advancing
        # by 1 per round while spec slots advance by accept-count);
        # otherwise one sampled resident selects the sampled program
        # (greedy co-tenants ride its argmax lane); an all-greedy
        # pool keeps the cheapest argmax-only program.
        spec_ks = [s.sampling.spec_k for s in self._resident.values()
                   if s.sampling.speculative]
        if spec_ks:
            self._decode_step_spec(window, max(spec_ks))
            return
        if self.paged and self.slots.lazy \
                and not self._ensure_lazy_growth(window):
            # Exhaustion preemptions consumed this boundary (the
            # resident set mutated); the next tick re-plans with the
            # survivors' grown tables.
            return
        sampled = any(s.sampling.sampled
                      for s in self._resident.values())
        occupancy = len(self._resident)
        if self.recorder is not None:
            self.recorder.on_step_start()
        t0 = time.perf_counter()

        def dispatch():
            with self.device_lock:
                return self.slots.step(window, sampled)  # [W, S]

        toks_w = self._dispatch_step(dispatch)
        if toks_w is None:
            # Containment resolved the boundary by mutating the
            # resident set (quarantine evictions / a conviction)
            # instead of producing tokens — the next tick re-plans.
            if self.recorder is not None:
                self.recorder.on_step_end(0)
            return
        t1 = time.perf_counter()
        self.decode_steps_total += window
        emitted = 0
        for slot, stream in list(self._resident.items()):
            for w in range(window):
                stream.out.append(int(toks_w[w, slot]))
                emitted += 1
                if stream.done():
                    break
            if stream.done():
                del self._resident[slot]
                self.slots.release(slot)
                self.evicted_total += 1
                self._note_freed(stream, "complete")
                self._complete(stream)   # records the slot id
                stream.slot = None
        self.step_device_s_total += self.slots.last_step_device_s
        self.step_wall_s_total += t1 - t0
        if self.recorder is not None:
            self.recorder.on_step_end(emitted)
        self.tel.step("step", t0, t1,
                      kind="sampled" if sampled else "plain",
                      window=window, occupancy=occupancy,
                      batch=self.slots.n_slots, tokens=emitted,
                      device_s=round(self.slots.last_step_device_s,
                                     6),
                      **({"mesh": self.mesh.axes_str()}
                         if self.mesh is not None else {}),
                      **({"pages_free": self.slots.free_page_count(),
                          "pages_total": self.slots.n_pages}
                         if self.paged else {}))

    def _decode_step_spec(self, window: int, K: int) -> None:
        """Advance the pool by ``window`` fused SPECULATIVE rounds
        (program width ``K`` = the largest resident spec_k).  Each
        spec slot commits its own accepted prefix per round —
        variable advance — while non-spec co-tenants commit exactly
        one token per round; budgets are accounted in COMMITTED
        tokens, and a stream stops consuming at its own eos/budget
        (later tokens are discardable garbage, exactly like the
        windowed plain step)."""
        if self.paged and self.slots.lazy \
                and not self._ensure_lazy_growth(window * K + 1):
            # A spec round's verify chunk writes up to window*K+1
            # positions past the last committed token — grow (or
            # preempt) for the whole span before dispatch.
            return
        occupancy = len(self._resident)
        if self.recorder is not None:
            self.recorder.on_step_start()
        t0 = time.perf_counter()

        def dispatch():
            with self.device_lock:
                return self.slots.step_spec(window, K)

        out = self._dispatch_step(dispatch)
        if out is None:
            # Containment mutated the resident set instead of
            # producing tokens — the next tick re-plans (see the
            # plain step).
            if self.recorder is not None:
                self.recorder.on_step_end(0)
            return
        toks, commits, accepts = out
        t1 = time.perf_counter()
        self.decode_steps_total += window
        self.spec_rounds_total += window
        emitted = accepted = 0
        for slot, stream in list(self._resident.items()):
            spec = stream.sampling.speculative
            for w in range(window):
                c = int(commits[w, slot])
                if spec:
                    stream.spec_rounds += 1
                    stream.spec_drafted += stream.sampling.spec_k
                    stream.spec_accepted += int(accepts[w, slot])
                    self.spec_drafted_total += stream.sampling.spec_k
                    self.spec_accepted_total += int(accepts[w, slot])
                    accepted += int(accepts[w, slot])
                for j in range(c):
                    stream.out.append(int(toks[w, slot, j]))
                    emitted += 1
                    if stream.done():
                        break
                if stream.done():
                    break
            if stream.done():
                del self._resident[slot]
                self.slots.release(slot)
                self.evicted_total += 1
                self._note_freed(stream, "complete")
                self._complete(stream)   # records the slot id
                stream.slot = None
        self.step_device_s_total += self.slots.last_step_device_s
        self.step_wall_s_total += t1 - t0
        if self.recorder is not None:
            self.recorder.on_step_end(emitted)
        self.tel.step("step", t0, t1, kind="spec", window=window,
                      k=K, occupancy=occupancy,
                      batch=self.slots.n_slots, tokens=emitted,
                      accepted=accepted,
                      device_s=round(self.slots.last_step_device_s,
                                     6),
                      **({"mesh": self.mesh.axes_str()}
                         if self.mesh is not None else {}),
                      **({"pages_free": self.slots.free_page_count(),
                          "pages_total": self.slots.n_pages}
                         if self.paged else {}))

    # -- completion -----------------------------------------------------

    def _complete(self, stream: Stream) -> None:
        group = stream.group
        stream.t_done = time.perf_counter()
        if stream.sampling.speculative and stream.spec_drafted:
            # One acceptance-rate observation per completed stream:
            # accepted draft tokens / drafted (the correction token a
            # rejection commits is not "accepted" work).
            self.spec_accept.observe(
                stream.spec_accepted / stream.spec_drafted)
        # Lifecycle tail: one decode span (admission -> done) plus the
        # completion instant — per-window detail lives on the engine
        # step track, keyed back by the slot id.
        if stream.t_admit is not None:
            args = {"row": stream.row, "slot": stream.slot,
                    "tokens": len(stream.out)}
            if stream.preempts or stream.resumes:
                # A resumed request must be distinguishable from a
                # straight-through one in the trace (the satellite
                # fix — the access log gets the same fields).
                args.update(preempts=stream.preempts,
                            resumes=stream.resumes)
            if stream.sampling.speculative:
                args.update(spec_rounds=stream.spec_rounds,
                            spec_drafted=stream.spec_drafted,
                            spec_accepted=stream.spec_accepted)
            self._emit(stream, "decode", stream.t_admit,
                       stream.t_done, **args)
        self._emit_instant(stream, "complete", stream.t_done,
                           row=stream.row, tokens=len(stream.out))
        group.complete_row(stream)
        if group.event.is_set() and group.error is None:
            self.completed_total += 1
            if group.sampling.speculative:
                self.completed_spec_total += 1
            elif group.sampling.sampled:
                self.completed_sampled_total += 1
            else:
                self.completed_greedy_total += 1
            self._record_history(group)

    def _fail_group(self, group: RequestGroup,
                    err: BaseException) -> None:
        """Deliver ``err`` to every thread waiting on ``group`` and
        reclaim its resources; OTHER groups' streams keep running (a
        stranger's OOM must not kill the batch)."""
        self.queue.drop_group(group)
        for slot, stream in list(self._resident.items()):
            if stream.group is group:
                del self._resident[slot]
                self.slots.release(slot)
                self.evicted_total += 1
        for stream in group.streams:
            self._release_stream_kv(stream)
        if not group.event.is_set():   # fail once, however many
            t = time.perf_counter()    # streams drag the group down
            for stream in group.streams:
                self._emit_instant(stream, "fail", t,
                                   row=stream.row,
                                   error=type(err).__name__)
        group.fail(err)
        self._record_history(group)

    # -- introspection --------------------------------------------------

    @staticmethod
    def _kind_of(sampling: SamplingSpec) -> str:
        if sampling.speculative:
            return "speculative"
        return "sampled" if sampling.sampled else "greedy"

    def _record_history(self, group: RequestGroup) -> None:
        """One terminal record per request into the retention ring —
        the full causal story ``GET /requests/<id>`` serves.  Called
        on every terminal path (complete / cancel / expire / shed /
        fail); re-recording the same request ID replaces the older
        record, so double calls on shutdown races are harmless."""
        h = self.history
        if group.rid is None:
            return
        t_done = group.t_done if group.t_done is not None \
            else time.perf_counter()
        # Phase ledger (serving/forensics.py): ONE computation over
        # the union of the group's stream events feeds the history
        # record, the sentry, and (via the same function at the
        # front-end) the timings block — the partition cannot drift
        # between surfaces.  Computed whenever a consumer is armed,
        # even with the history ring off.
        ledger = None
        if self.forensics is not None or (h is not None
                                          and h.enabled):
            all_events: list = []
            for s in group.streams:
                if s.events:
                    all_events.extend(s.events)
            ledger = compute_ledger(all_events, group.t_submit,
                                    t_done)
            if self.forensics is not None:
                self.forensics.note(ledger, group.rid)
        if h is None or not h.enabled:
            return
        queue_s, prefill_s, decode_s = group.breakdown()
        rec: Dict[str, Any] = {
            "request_id": group.rid,
            "t": round(time.time(), 3),
            "status": group.status,
            "kind": self._kind_of(group.sampling),
            "priority": group.priority,
            "rows": len(group.streams),
            "prompt_tokens": int(group.rows.shape[1]),
            "max_new_tokens": int(group.new),
            "wall_s": round(max(0.0, t_done - group.t_submit), 6),
            "queue_wait_s": round(queue_s, 6),
            "prefill_s": round(prefill_s, 6),
            "decode_s": round(decode_s, 6),
            "preempts": sum(s.preempts for s in group.streams),
            "resumes": sum(s.resumes for s in group.streams),
        }
        if group.t_first_admit is not None:
            rec["ttft_s"] = round(
                group.t_first_admit - group.t_submit, 6)
        if group.error is not None:
            rec["error"] = (f"{type(group.error).__name__}: "
                            f"{group.error}")[:300]
        if group.prefix_info:
            rec["prefix"] = dict(group.prefix_info)
        if group.sampling.speculative:
            rec["spec"] = {
                "rounds": sum(s.spec_rounds for s in group.streams),
                "drafted": sum(s.spec_drafted
                               for s in group.streams),
                "accepted": sum(s.spec_accepted
                                for s in group.streams)}
        if ledger is not None:
            rec["phases"] = ledger
        rec["streams"] = [
            {"row": s.row,
             "tokens_out": len(s.out),
             **({"slot": s.last_slot}
                if s.last_slot is not None else {}),
             **({"preempts": s.preempts, "resumes": s.resumes}
                if (s.preempts or s.resumes) else {}),
             "timeline": events_to_dicts(s.events or [],
                                         group.t_submit)}
            for s in group.streams]
        h.record(rec)

    def build_debug_snapshot(self, forced: bool = False
                             ) -> Dict[str, Any]:
        """The ``/debug/state`` snapshot: slot table, per-class
        queues with entry ages, page pool, lifecycle flags — plain
        host-side dicts, NEVER the device lock (the SNAPSHOT-LOCK
        contract, docs/DESIGN.md).  Normally built on the engine
        thread at a step boundary (tick), so it is internally
        consistent; ``forced=True`` marks a build from another thread
        (the stall watchdog, whose whole premise is that the engine
        thread is stuck) — best-effort, possibly mid-mutation."""
        now = time.perf_counter()
        slots = []
        for slot, s in sorted(list(self._resident.items())):
            slots.append({
                "slot": slot,
                "request_id": s.group.rid,
                "row": s.row,
                "kind": self._kind_of(s.sampling),
                "priority": s.group.priority,
                "position": s.p_len + len(s.out) - 1,
                "tokens_out": len(s.out),
                "remaining": s.new - len(s.out),
                "preempts": s.preempts,
                "resumes": s.resumes,
                "age_s": round(now - s.group.t_submit, 3),
                **({"deadline_in_s": round(
                    s.group.deadline - now, 3)}
                   if s.group.deadline is not None else {}),
            })
        queues: Dict[str, list] = {p: [] for p in PRIORITIES}
        for s in self.queue.snapshot():
            queues[s.group.priority].append({
                "request_id": s.group.rid,
                "row": s.row,
                "age_s": round(now - s.group.t_submit, 3),
                "prefilled": s.filled,
                "prompt_tokens": s.p_len,
                "pf_done": s.pf_done,
                **({"blocked_s": round(now - s.blocked_t, 3)}
                   if s.blocked_t is not None else {}),
            })
        snap: Dict[str, Any] = {
            "t": now,
            "forced": bool(forced),
            "draining": self.draining,
            "n_slots": self.slots.n_slots,
            "free_slots": self.slots.free_slots,
            "slots": slots,
            "queues": queues,
            "queue_len": sum(len(q) for q in queues.values()),
            "last_step_age_s": round(
                max(0.0, now - self.last_boundary_t), 3),
            "decode_steps_total": self.decode_steps_total,
        }
        if self.paged:
            snap["pages"] = {**self.slots.page_stats(),
                             "slot_table_pages":
                                 self.slots.slot_page_counts()}
        if self.mesh is not None:
            snap["mesh"] = self.mesh.axes_str()
        # Fault-tolerance state: the supervisor block (restart
        # count, breaker state, last crash/recovery evidence) and
        # the armed fault plan's injection counters ride every
        # snapshot — so a recovery storm is diagnosable from ONE
        # artifact (/debug/state, or the stall watchdog's bundle,
        # which embeds a forced build of this same snapshot).
        snap["engine_down"] = self.down
        if self.supervisor is not None:
            snap["supervisor"] = self.supervisor.status()
        if self.faults is not None:
            snap["faults"] = self.faults.stats()
        if self._suspects:
            snap["quarantine_suspects"] = sorted(
                g.rid for g in self._suspects if g.rid)
        return snap

    def stats(self) -> Dict[str, Any]:
        # Per-request queue/prefill/decode timing lives in ModelServer
        # (_note_breakdown, fed from group.breakdown()) — one source
        # of truth for /metrics; the engine exposes scheduling
        # counters only.
        fstats = self.faults.stats() if self.faults is not None \
            else None       # one lock-guarded build per scrape
        return {
            "slots": self.slots.n_slots,
            "slots_active": self.slots.active_slots,
            "slot_occupancy": round(
                self.slots.active_slots / self.slots.n_slots, 4),
            "queue_len": len(self.queue),
            "queue_depth": self.policy.queue_depth,
            "admitted_total": self.admitted_total,
            "admitted_greedy_total": self.admitted_greedy_total,
            "admitted_sampled_total": self.admitted_sampled_total,
            "admitted_spec_total": self.admitted_spec_total,
            "evicted_total": self.evicted_total,
            "decode_steps_total": self.decode_steps_total,
            "prefill_chunks_total": self.prefill_chunks_total,
            "completed_total": self.completed_total,
            "completed_greedy_total": self.completed_greedy_total,
            "completed_sampled_total": self.completed_sampled_total,
            "completed_spec_total": self.completed_spec_total,
            "rejected_total": self.queue.rejected,
            # Request lifecycle: terminal-status counters, the
            # preempt/resume pair (equal in steady state — every
            # preempted stream resumes unless its group dies first),
            # per-class admission split + queue depths, and the
            # drain latch.
            "cancelled_total": self.cancelled_total,
            "expired_total": self.expired_total,
            "shed_total": self.shed_total,
            "shed_kv_pages_total": self.shed_kv_pages_total,
            "shed_interactive_total":
                self.shed_by_class["interactive"],
            "shed_batch_total": self.shed_by_class["batch"],
            "preempted_total": self.preempted_total,
            "resumed_total": self.resumed_total,
            # Lazy-KV exhaustion preemptions (0 unless --kv-lazy):
            # residents evicted mid-decode because a co-tenant's page
            # growth found the pool empty (engine._ensure_lazy_growth)
            # — a subset of preempted_total.
            "kv_preempt_exhaustion_total":
                self.kv_preempt_exhaustion_total,
            "admitted_interactive_total":
                self.admitted_by_class["interactive"],
            "admitted_batch_total": self.admitted_by_class["batch"],
            "queue_len_interactive":
                self.queue.class_len("interactive"),
            "queue_len_batch": self.queue.class_len("batch"),
            "draining": self.draining,
            # Fault tolerance (serving/faults.py + recovery.py):
            # step-retry / requeue-and-resume / quarantine-conviction
            # counters, the supervisor's crash/restart totals and
            # breaker state, and the armed fault plan's per-site
            # injection counters — ONE dict behind /metrics AND
            # /info (the no-drift pin, tests/test_faults.py).
            "engine_down": self.down,
            "step_retries_total": self.step_retries_total,
            "requests_requeued_total": self.requests_requeued_total,
            "poisoned_total": self.poisoned_total,
            "telemetry_errors_total": self.telemetry_errors_total,
            "engine_crashes_total":
                self.supervisor.crashes_total
                if self.supervisor is not None else 0,
            "engine_restarts_total":
                self.supervisor.restarts_total
                if self.supervisor is not None else 0,
            "breaker_state":
                self.supervisor.breaker.state
                if self.supervisor is not None else "unsupervised",
            "faults_injected_total":
                fstats["faults_injected_total"]
                if fstats is not None else 0,
            "faults_injected":
                fstats["faults_injected"]
                if fstats is not None else {},
            # Speculative scheduling + the per-request acceptance-rate
            # histogram (per-bucket counts, upper bounds in
            # spec_accept_buckets; /metrics cumulates them via
            # telemetry.render_histogram) — ONE structure behind both
            # observability endpoints.
            "spec_rounds_total": self.spec_rounds_total,
            "spec_drafted_total": self.spec_drafted_total,
            "spec_accepted_total": self.spec_accepted_total,
            **self._spec_accept_stats(),
            # Paged-KV page-pool gauges (absent in fixed-lane mode):
            # free/resident/shared page counts — the occupancy story
            # the paged refactor exists for, fed to /metrics + /info
            # from this ONE dict.
            **(self.slots.page_stats() if self.paged else {}),
            # Mesh topology + step device/wall seconds (absent
            # unmeshed): axis names/sizes and device count for
            # /info, and the cumulative per-dispatch device share —
            # on a mesh the device wall bundles compute AND
            # collectives, so the tp=1-vs-tpN bench A/B is what
            # isolates the collective-time share (bench_serving_load
            # meshed leg).
            **(self._mesh_stats() if self.mesh is not None else {}),
            # Recompile sentinel: compile_cache_misses must go quiet
            # once traffic has warmed its shapes (the zero-steady-
            # state contract, tests/test_analysis.py); a counter that
            # keeps climbing under same-shaped load is a recompile
            # storm.
            **self.sentinel.snapshot(),
        }

    def _mesh_stats(self) -> Dict[str, Any]:
        wall = self.step_wall_s_total
        return {
            "mesh": self.mesh.describe(),
            "mesh_devices": self.mesh.n_devices,
            "step_device_seconds_total":
                round(self.step_device_s_total, 6),
            "step_wall_seconds_total": round(wall, 6),
            # Per-step device share of the dispatch wall: the
            # remainder is host scheduling; the device part bundles
            # per-shard compute + collectives (see stats() note).
            "step_device_share":
                round(self.step_device_s_total / wall, 4)
                if wall > 0 else None,
        }

    def _spec_accept_stats(self) -> Dict[str, Any]:
        counts, total, n = self.spec_accept.snapshot()
        return {
            "spec_accept_buckets": list(self.spec_accept.buckets),
            "spec_accept_hist": counts,
            "spec_accept_sum": round(total, 6),
            "spec_accept_count": n,
        }
