"""Radix (compressed token-trie) index for the serving prefix cache.

The seed prefix cache was a flat OrderedDict scanned linearly under
``_prefix_lock`` — O(entries x prompt) token comparisons per lookup,
fine at 4 entries, hostile at the entry counts a system-prompt fleet
wants.  This index stores entries in a compressed trie over token
COLUMNS (a batch-``b`` prompt is a sequence of b-wide columns, so
multi-row prompts radix exactly like single-row ones), giving:

- ``lookup``: longest stored entry that prefixes the query in one
  O(prompt) walk, whatever the entry count;
- ``store``: path-splitting insert that also returns the DEEPEST
  ancestor entry already stored — the hook the paged-KV prefix store
  uses to share page-aligned prefix pages between entries (a stored
  system prompt's pages are referenced, not recopied, by every
  session extension stored on top of it);
- LRU eviction over ENTRIES with structural pruning: evicting an
  entry removes its node (and any childless chain above it) but
  never touches descendants — payload-level sharing (page refcounts)
  is the owner's concern, reported back via the evicted payloads.

TWO-TIER STORE SUPPORT: the index itself is tier-agnostic (a payload
is opaque), but the host-RAM spill tier (ModelServer, PR 12) needs
two more primitives so an entry can be DEMOTED in place — its device
pages spilled to pinned host buffers — instead of dropped:

- :meth:`set_payload` swaps one entry's payload without touching its
  recency position (with an identity guard, so a concurrent
  overwrite is never clobbered by a stale demotion);
- :meth:`remove` pops one EXACT entry (the byte-budget eviction of
  the host tier, and the recovery flush's survivor rebuild).

Thread-safety is the CALLER's: ModelServer wraps every call in its
``_prefix_lock`` exactly as it wrapped the flat dict.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = ["RadixPrefixIndex"]


class _Node:
    __slots__ = ("edge", "children", "entry", "parent", "hits")

    def __init__(self, edge: np.ndarray,
                 parent: Optional["_Node"]):
        self.edge = edge                 # [b, m] tokens from parent
        self.children = {}               # first-column bytes -> _Node
        self.entry: Optional[Tuple[np.ndarray, Any]] = None
        self.parent = parent
        # Lifetime hit count for the entry stored HERE (0 until a
        # lookup lands on it) — the fleet eviction policy's "which
        # copy is the hot one" signal (entries_meta).
        self.hits = 0


def _col_key(toks: np.ndarray, i: int) -> bytes:
    return toks[:, i].tobytes()


class RadixPrefixIndex:
    """LRU-bounded radix index: token matrix [b, n] -> payload."""

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._roots = {}                 # batch size -> root _Node
        # Two recency rings over entries (key = (b, len, bytes) ->
        # entry node): HOT holds explicit registrations and anything
        # a lookup ever hit (LRU order); COLD holds speculative
        # session store-backs that no lookup has touched yet (FIFO —
        # oldest first).  Eviction drains COLD before touching HOT,
        # so one-shot store-backs cycle among THEMSELVES instead of
        # flushing a registered system prompt (scan resistance), and
        # a cold entry that proves useful is promoted on its first
        # hit.
        self._hot: "OrderedDict[tuple, _Node]" = OrderedDict()
        self._cold: "OrderedDict[tuple, _Node]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._hot) + len(self._cold)

    @staticmethod
    def _key(toks: np.ndarray) -> tuple:
        return (toks.shape[0], toks.shape[1], toks.tobytes())

    def _match_walk(self, toks: np.ndarray):
        """Walk as deep as full edges match ``toks``; returns
        ``(node, depth, best)`` where ``best`` is the deepest
        fully-matched node holding an entry (or None)."""
        b, n = toks.shape
        node = self._roots.get(b)
        depth, best = 0, None
        while node is not None:
            if node.entry is not None:
                best = node
            if depth >= n:
                break
            child = node.children.get(_col_key(toks, depth))
            if child is None:
                break
            m = child.edge.shape[1]
            if depth + m > n or not np.array_equal(
                    child.edge, toks[:, depth:depth + m]):
                break
            node, depth = child, depth + m
        return node, depth, best

    def _promote(self, key) -> None:
        """A hit makes an entry HOT (and freshest) wherever it was."""
        node = self._cold.pop(key, None)
        if node is not None:
            self._hot[key] = node
        else:
            self._hot.move_to_end(key)

    def lookup(self, toks: np.ndarray
               ) -> Optional[Tuple[np.ndarray, Any]]:
        """Longest stored entry whose prompt is a prefix of ``toks``
        (same batch width): ``(entry_tokens, payload)`` or None.
        Refreshes the hit's recency (cold entries promote to hot)."""
        _, _, best = self._match_walk(np.ascontiguousarray(toks))
        if best is None:
            return None
        ent_toks, payload = best.entry
        best.hits += 1
        self._promote(self._key(ent_toks))
        return ent_toks, payload

    def longest_ancestor(self, toks: np.ndarray
                         ) -> Optional[Tuple[np.ndarray, Any]]:
        """Deepest stored entry that strictly or exactly prefixes
        ``toks`` — the page-sharing parent for a store.  Does NOT
        refresh LRU (a store is not a hit)."""
        _, _, best = self._match_walk(np.ascontiguousarray(toks))
        return best.entry if best is not None else None

    def store(self, toks: np.ndarray, payload, *, hot: bool = True
              ) -> List[Tuple[np.ndarray, Any]]:
        """Insert/overwrite the entry for ``toks``; returns the
        DISPLACED payload entries — the overwritten same-prompt entry
        (if any) plus LRU evictions past ``cap`` — for the caller to
        free (unpin pages / drop caches).

        ``hot=False`` inserts into the COLD ring (scan resistance):
        speculative session store-backs — one per served request —
        evict each other FIFO instead of flushing a deliberately
        registered system prompt, which a stream of one-shot
        suffixes would otherwise evict within ``cap`` requests.  A
        later lookup hit promotes a cold entry to hot like any
        other.  When the index is at capacity with every OTHER entry
        hot, a cold insert cannot survive (hot entries outrank
        speculation) — :meth:`accepts` lets callers skip the store's
        expensive side effects up front in that case."""
        toks = np.ascontiguousarray(np.asarray(toks, np.int32))
        b, n = toks.shape
        displaced: List[Tuple[np.ndarray, Any]] = []
        root = self._roots.get(b)
        if root is None:
            root = self._roots[b] = _Node(
                np.zeros((b, 0), np.int32), None)
        node, depth = root, 0
        while depth < n:
            child = node.children.get(_col_key(toks, depth))
            if child is None:
                leaf = _Node(toks[:, depth:].copy(), node)
                node.children[_col_key(toks, depth)] = leaf
                node, depth = leaf, n
                break
            m_max = child.edge.shape[1]
            rem = toks[:, depth:]
            m = 0
            while m < m_max and m < rem.shape[1] and \
                    np.array_equal(child.edge[:, m], rem[:, m]):
                m += 1
            if m == m_max:
                node, depth = child, depth + m
                continue
            # Split child's edge at m: node -> mid -> child.
            mid = _Node(child.edge[:, :m].copy(), node)
            node.children[_col_key(toks, depth)] = mid
            child.edge = child.edge[:, m:].copy()
            child.parent = mid
            mid.children[child.edge[:, 0].tobytes()] = child
            if depth + m == n:
                node, depth = mid, n
                break
            leaf = _Node(toks[:, depth + m:].copy(), mid)
            mid.children[_col_key(toks, depth + m)] = leaf
            node, depth = leaf, n
            break
        key = self._key(toks)
        if node.entry is not None:
            displaced.append(node.entry)
            old_key = self._key(node.entry[0])
            self._hot.pop(old_key, None)
            self._cold.pop(old_key, None)
        node.entry = (toks, payload)
        ring = self._hot if hot else self._cold
        ring[key] = node
        ring.move_to_end(key)
        while len(self) > self.cap:
            ev = self.pop_lru()
            if ev is None:
                break
            displaced.append(ev)
        return displaced

    def accepts(self, hot: bool = True) -> bool:
        """Whether a NEW entry of this hotness could survive
        insertion: a cold insert into an index whose capacity is
        fully held by hot entries is evicted in the same call —
        callers with expensive store side effects (the paged page
        scatter) check first."""
        return hot or len(self._hot) < self.cap

    def pop_lru(self) -> Optional[Tuple[np.ndarray, Any]]:
        """Evict the coldest entry (oldest COLD store-back first,
        then least-recently-hit HOT entry); prunes its node chain and
        returns ``(tokens, payload)`` for the caller to free, or None
        when empty.  Structural only: descendants — deeper entries
        whose payloads may share this entry's pages — are untouched;
        the page refcounts decide what memory actually frees."""
        if self._cold:
            _, node = self._cold.popitem(last=False)
        elif self._hot:
            _, node = self._hot.popitem(last=False)
        else:
            return None
        entry = node.entry
        node.entry = None
        # Prune childless, entry-less nodes upward.
        while node.parent is not None and node.entry is None \
                and not node.children:
            parent = node.parent
            parent.children.pop(node.edge[:, 0].tobytes(), None)
            node = parent
        return entry

    def _exact_node(self, toks: np.ndarray) -> Optional[_Node]:
        """The node holding EXACTLY ``toks``'s entry, or None."""
        toks = np.ascontiguousarray(np.asarray(toks, np.int32))
        node, depth, _ = self._match_walk(toks)
        if node is None or depth != toks.shape[1] \
                or node.entry is None \
                or node.entry[0].shape != toks.shape \
                or not np.array_equal(node.entry[0], toks):
            return None
        return node

    def set_payload(self, toks: np.ndarray, payload, *,
                    expect=None) -> bool:
        """Swap the payload of the EXACT entry for ``toks`` in place
        (recency position untouched) — the tier-demotion/promotion
        primitive.  With ``expect`` set, the swap only happens while
        the current payload IS ``expect`` (identity), so a demotion
        computed outside the caller's lock can never clobber an
        entry that was overwritten meanwhile.  Returns whether the
        swap happened."""
        node = self._exact_node(toks)
        if node is None:
            return False
        if expect is not None and node.entry[1] is not expect:
            return False
        node.entry = (node.entry[0], payload)
        return True

    def remove(self, toks: np.ndarray) -> Optional[Any]:
        """Pop the EXACT entry for ``toks`` (structural pruning like
        pop_lru; descendants untouched); returns its payload, or
        None when not stored."""
        node = self._exact_node(toks)
        if node is None:
            return None
        key = self._key(node.entry[0])
        self._hot.pop(key, None)
        self._cold.pop(key, None)
        payload = node.entry[1]
        node.entry = None
        while node.parent is not None and node.entry is None \
                and not node.children:
            parent = node.parent
            parent.children.pop(node.edge[:, 0].tobytes(), None)
            node = parent
        return payload

    def entries(self) -> List[Tuple[np.ndarray, Any]]:
        """Every stored entry, eviction order (coldest first)."""
        return [n.entry
                for ring in (self._cold, self._hot)
                for n in ring.values() if n.entry is not None]

    def entries_meta(self) -> List[Tuple[np.ndarray, Any, int, bool]]:
        """Every stored entry with its recency metadata, eviction
        order (coldest first): ``(tokens, payload, hits, hot)``.
        The fleet prefix-index endpoint reads this — hit counts and
        ring membership are what the router's one-copy-somewhere
        eviction pass ranks duplicate copies by."""
        out: List[Tuple[np.ndarray, Any, int, bool]] = []
        for ring, hot in ((self._cold, False), (self._hot, True)):
            for n in ring.values():
                if n.entry is not None:
                    out.append((n.entry[0], n.entry[1],
                                n.hits, hot))
        return out
