"""Command-line interface (``ptpu``)."""
