"""``python -m polyaxon_tpu.cli`` — same entrypoint as the ``ptpu``
console script."""

from .main import cli

if __name__ == "__main__":
    cli()
