"""CLI: the L9 surface (SURVEY.md 2.1).

Command tree parity with the reference (`polyaxon run/ops/config/version`
et al.), TPU-first semantics: local mode executes in-process against the
file store; API mode (POLYAXON_TPU_HOST) goes through the control plane.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

import click

from polyaxon_tpu import __version__


def _parse_params(params: Tuple[str, ...]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for item in params:
        if "=" not in item:
            raise click.BadParameter(
                f"-P expects name=value, got {item!r}")
        key, _, value = item.partition("=")
        out[key.strip()] = value
    return out


def _echo_record(record: Dict[str, Any], fields: Optional[List[str]] = None):
    fields = fields or ["uuid", "name", "kind", "status", "created_at",
                        "duration"]
    for f in fields:
        click.echo(f"{f:>12}: {record.get(f)}")


@click.group(name="ptpu")
@click.version_option(version=__version__, prog_name="polyaxon-tpu")
def cli():
    """polyaxon-tpu: TPU-native ML orchestration.

    Declarative specs -> compile -> run (local or TPU slices) -> track ->
    tune -> stream.
    """


# ---------------------------------------------------------------------------
# run
# ---------------------------------------------------------------------------


@cli.command()
@click.option("-f", "--file", "files", multiple=True, required=True,
              type=click.Path(), help="Polyaxonfile(s) to run (merged in order).")
@click.option("-P", "--param", "params", multiple=True,
              help="Param override: -P lr=0.1 (repeatable).")
@click.option("--preset", "presets", multiple=True, type=click.Path(),
              help="Preset file(s) applied before -P params.")
@click.option("--name", default=None, help="Run name override.")
@click.option("--project", default="default", help="Project name.")
@click.option("--watch/--no-watch", default=True,
              help="Stream logs while running (local mode).")
@click.option("--eager", is_flag=True, default=False,
              help="Force local in-process execution even in API mode.")
@click.option("--check-only", is_flag=True, default=False,
              help="Validate and print the operation without running.")
@click.option("--queue", default=None,
              help="Queue override (API mode; else from the spec).")
@click.option("--priority", default=None, type=int,
              help="Priority override, higher claims first (API mode).")
def run(files, params, presets, name, project, watch, eager, check_only,
        queue, priority):
    """Run a polyaxonfile: compile, execute, track."""
    from polyaxon_tpu.polyaxonfile import check_polyaxonfile
    from polyaxon_tpu.polyaxonfile.reader import PolyaxonfileError

    try:
        op = check_polyaxonfile(list(files), params=_parse_params(params),
                                presets=list(presets) or None)
    except (PolyaxonfileError, ValueError) as e:
        raise click.ClickException(f"Invalid polyaxonfile: {e}")

    if check_only:
        click.echo(json.dumps(op.to_dict(), indent=2, default=str))
        return

    host = os.environ.get("POLYAXON_TPU_HOST")
    if host and not eager:
        from polyaxon_tpu.client import RunClient

        client = RunClient(project=project)
        record = client.create(name=name or op.name, content=op.to_dict(),
                               kind=getattr(op.component.run, "kind", None)
                               if op.has_component else None,
                               managed_by="agent",
                               queue=queue or op.effective_queue,
                               priority=priority if priority is not None
                               else op.effective_priority)
        client.log_status("queued", reason="CliSubmit", force=True)
        click.echo(f"Run {record['uuid']} queued on {host}")
        return

    from polyaxon_tpu.runner import LocalExecutor

    if queue or priority is not None:
        click.echo("note: --queue/--priority apply to queued (API-mode) "
                   "submission; this local run executes immediately.",
                   err=True)
    if name:
        op = op.model_copy(update={"name": name})
    executor = LocalExecutor(project=project, stream_logs=watch)
    try:
        record = executor.run_operation(op)
    except Exception as e:
        raise click.ClickException(f"Run failed: {e}")
    status = record.get("status")
    _echo_record(record)
    if status == "running" and record.get("kind") == "service":
        # RUNNING is the service's steady state, not a failure: it
        # stays up detached until `ops stop` reaps it.
        svc = (record.get("meta_info") or {}).get("service") or {}
        ports = svc.get("ports") or []
        where = f" on port {ports[0]}" if ports else ""
        click.echo(f"service is up{where}; stop with "
                   f"`ptpu ops stop {record['uuid']}`")
        return
    if status != "succeeded":
        logs = executor.store.read_logs(record["uuid"], tail=20)
        if logs:
            click.echo("--- last logs ---")
            click.echo(logs)
        raise click.ClickException(f"Run finished with status {status!r}")


# ---------------------------------------------------------------------------
# generate (serving)
# ---------------------------------------------------------------------------


def _parse_prompt(prompt: str):
    """``"1,2,3"`` -> one row; ``@file.json`` -> list of rows (all the
    same length — ragged prompts must be padded upstream)."""
    import json as _json

    if prompt.startswith("@"):
        try:
            with open(prompt[1:]) as f:
                rows = _json.load(f)
        except (OSError, ValueError) as e:
            raise click.ClickException(
                f"cannot read prompt file {prompt[1:]!r}: {e}")
        if not isinstance(rows, list):
            raise click.ClickException(
                "prompt file must hold a JSON list of token ids or a "
                "list of rows")
        if not rows or not isinstance(rows[0], list):
            rows = [rows]
    else:
        rows = [[t for t in prompt.split(",") if t.strip()]]
    try:
        rows = [[int(t) for t in r] for r in rows]
    except (TypeError, ValueError) as e:
        raise click.ClickException(
            f"prompt rows must contain integer token ids: {e}")
    if not rows or not rows[0]:
        raise click.ClickException("prompt must contain at least one "
                                   "token id")
    if len({len(r) for r in rows}) != 1:
        raise click.ClickException(
            "All prompt rows must share one length (pad upstream)")
    return rows


def _build_serving_model(name: str, batch_size: int,
                         ckpt_dir, kv_int8: bool, int8_weights: bool,
                         kv_ring: bool = False, kv_ring_slack: int = 0):
    """Shared by ``generate`` and ``serve``: zoo model + variables
    with the serving options applied (int8 KV / ring-cache config,
    checkpoint restore, weight quantization)."""
    from polyaxon_tpu.models.registry import get_model

    spec = get_model(name)
    kw = {}
    if kv_int8:
        kw["kv_cache_int8"] = True
    if kv_ring:
        kw["kv_cache_ring"] = True
        if kv_ring_slack:
            # speculative decoding on a ring cache needs spare slots
            # for rollback overwrites (generate_speculative's guard)
            kw["kv_cache_ring_slack"] = int(kv_ring_slack)
    try:
        if ckpt_dir:
            # Restoring replaces the params — don't pay a full random
            # init just to discard it.
            model = spec.make_model(**kw)
            variables = None
        else:
            model, variables = spec.init_params(
                batch_size=batch_size, **kw)
    except TypeError:
        if kw:
            # Name only the fields the family actually lacks: a
            # combined --int8-kv --kv-ring on gpt2 fails on kv_ring
            # alone, and blaming both would point the user at the
            # wrong flag.
            import dataclasses as _dc

            cfg = getattr(spec.make_model(), "cfg", None)
            known = ({f.name for f in _dc.fields(cfg)}
                     if _dc.is_dataclass(cfg) else set())
            bad = sorted(k for k in kw if k not in known) or sorted(kw)
            raise click.ClickException(
                f"{name} does not support {bad} (no such config "
                f"field{'s' if len(bad) > 1 else ''} on this model "
                f"family)")
        # No config kwarg was passed, so the TypeError is a real bug
        # inside model construction — masking it as a quantization
        # message would point the user at the wrong flag.
        raise
    except ValueError as e:
        if kw:
            # Config-level validation of a passed flag (e.g.
            # kv_cache_ring on a model without sliding_window) — a
            # clean CLI error, not a traceback.
            raise click.ClickException(str(e))
        # No serving flag was passed: a real library bug, keep the
        # stack (same contract as the TypeError branch above).
        raise
    if ckpt_dir:
        from polyaxon_tpu.checkpoint import CheckpointManager

        state = CheckpointManager(directory=ckpt_dir).restore()
        restored = state.get("params") if isinstance(state, dict) \
            else None
        if restored is None:
            raise click.ClickException(
                f"checkpoint under {ckpt_dir} has no 'params'")
        # Train state stores the full flax variables dict under
        # "params" (TrainStep.init_state) — don't re-wrap it.
        variables = restored if isinstance(restored, dict) \
            and "params" in restored else {"params": restored}
    if int8_weights:
        from polyaxon_tpu.ops.quant import quantize_params

        variables = {"params": quantize_params(variables["params"])}
    return model, variables


@cli.command()
@click.option("--model", "model_name", required=True,
              help="Zoo model name (see models/registry.py).")
@click.option("--prompt", required=True,
              help="Comma-separated token ids, or @file.json with a "
                   "list of rows.")
@click.option("--max-new-tokens", default=32, type=int)
@click.option("--temperature", default=0.0, type=float,
              help="0 = greedy.")
@click.option("--top-k", default=None, type=int)
@click.option("--top-p", default=None, type=float,
              help="Nucleus sampling mass.")
@click.option("--beams", default=1, type=int,
              help=">1 switches to beam search (greedy scoring).")
@click.option("--eos-id", default=None, type=int)
@click.option("--checkpoint", default=None, type=click.Path(),
              help="Orbax checkpoint dir from `ptpu train` "
                   "(--checkpoint-every); default: random init.")
@click.option("--draft-model", "--spec-draft", "draft_model",
              default=None,
              help="Zoo model for SPECULATIVE decoding (same vocab; "
                   "--spec-draft is an alias). Greedy by default "
                   "(output identical to the target's greedy "
                   "decode); with --temperature it runs rejection "
                   "speculative sampling — exact target-distribution "
                   "samples for any draft, under the position-keyed "
                   "--seed schedule the server's engine uses.")
@click.option("--draft-checkpoint", default=None, type=click.Path())
@click.option("--spec-k", default=4, type=int,
              help="Draft proposals per speculative round.")
@click.option("--int8-weights", is_flag=True, default=False,
              help="Weight-only int8 (halves weight HBM reads).")
@click.option("--int8-kv", is_flag=True, default=False,
              help="int8 KV cache (halves KV HBM reads).")
@click.option("--kv-ring", is_flag=True, default=False,
              help="O(window) ring KV cache for sliding-window "
                   "models: stream past max_position (composes with "
                   "beam and --int8-kv).")
@click.option("--seed", default=0, type=int)
@click.option("--prefill-chunk", default=None, type=int,
              help="Prefill the prompt in fixed-size pieces to bound "
                   "activation memory (long prompts).")
@click.option("--cpu", is_flag=True, default=False)
def generate(model_name, prompt, max_new_tokens, temperature, top_k,
             top_p, beams, eos_id, checkpoint, draft_model,
             draft_checkpoint, spec_k, int8_weights, int8_kv,
             kv_ring, seed, prefill_chunk, cpu):
    """Decode with a zoo model — the native serving surface.

    The reference serves models as opaque user containers behind
    `V1Service`; here the framework owns the decode loop (compile-once
    scan, chunked prefill, KV cache), so sampling, beam search,
    speculative decoding and int8 serving are first-class flags.
    Emits one JSON object: tokens plus timing.
    """
    import json as _json
    import time as _time

    import jax

    if cpu:
        jax.config.update("jax_platforms", "cpu")
    from polyaxon_tpu.models import generate as G
    from polyaxon_tpu.models.registry import get_model

    rows = _parse_prompt(prompt)
    b = len(rows)

    # Speculative rounds on a ring cache overwrite up to k-1 still-
    # in-window slots on rollback: build both models with that slack
    # so --kv-ring + --draft-model works out of the box.
    ring_slack = (spec_k - 1) if (kv_ring and draft_model) else 0
    model, variables = _build_serving_model(
        model_name, b, checkpoint, int8_kv, int8_weights,
        kv_ring=kv_ring, kv_ring_slack=ring_slack)
    import numpy as np

    toks = np.asarray(rows, dtype=np.int32)
    t0 = _time.perf_counter()
    try:
        # Uniform sampling-param validation (same messages as the
        # server): an explicit --top-k 0 / --top-p 0 must be refused
        # on every decode path, not silently treated as "disabled" by
        # the positional branch's internal 0-encoding.
        G._check_top_k(top_k, getattr(getattr(model, "cfg", None),
                                      "vocab_size", None))
        G._check_top_p(top_p)
        if draft_model is not None:
            # Shared validation (ONE message with the server and the
            # library): spec_k >= 1, no speculative+beam.
            G._check_spec_k(spec_k)
            if beams > 1:
                raise click.ClickException(G.SPEC_BEAM_MSG)
            if temperature == 0.0 and (top_k is not None
                                       or top_p is not None):
                raise click.ClickException(
                    "speculative --top-k/--top-p need --temperature "
                    "> 0 (temperature=0 is greedy and would ignore "
                    "them)")
            draft, draft_vars = _build_serving_model(
                draft_model, b, draft_checkpoint, int8_kv,
                int8_weights, kv_ring=kv_ring,
                kv_ring_slack=ring_slack)
            # temperature>0 runs rejection speculative sampling under
            # the POSITION-KEYED --seed schedule (exact target-
            # distribution samples for any draft) — the same schedule
            # the server's engine and solo paths run, so `ptpu
            # generate --seed N` matches a served request with seed N.
            out = G.generate_speculative(
                model, variables, draft, draft_vars, toks,
                max_new_tokens=max_new_tokens, k=spec_k, eos_id=eos_id,
                prefill_chunk=prefill_chunk, temperature=temperature,
                top_k=top_k, top_p=top_p,
                seed=seed if temperature != 0.0 else None)
        elif beams > 1:
            if temperature != 0.0 or top_k is not None \
                    or top_p is not None:
                raise click.ClickException(
                    "beam search is deterministic (no --temperature, "
                    "--top-k or --top-p)")
            out = G.generate_beam(model, variables, toks,
                                  max_new_tokens=max_new_tokens,
                                  num_beams=beams, eos_id=eos_id,
                                  prefill_chunk=prefill_chunk)
        elif G.positional_eligible(model, temperature):
            # Decoder-only sampled decode uses the POSITION-KEYED
            # schedule (token i's key is a function of --seed, row,
            # and i alone), the same contract the server's
            # continuous-batching engine samples under — so `ptpu
            # generate --seed N` and a served request with seed N
            # return the same tokens.
            out = G.generate_positional(model, variables, toks,
                                        max_new_tokens=max_new_tokens,
                                        temperature=temperature,
                                        top_k=top_k, top_p=top_p,
                                        eos_id=eos_id, seed=seed,
                                        prefill_chunk=prefill_chunk)
        else:
            out = G.generate(model, variables, toks,
                             max_new_tokens=max_new_tokens,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p, eos_id=eos_id,
                             rng=jax.random.PRNGKey(seed),
                             prefill_chunk=prefill_chunk)
    except (ValueError, NotImplementedError) as e:
        # Library-level validation (max_position overflow, top_p
        # range, unsupported mode combinations like beam on unstacked
        # layers) — surface as a clean CLI error, not a traceback.
        raise click.ClickException(str(e))
    out = np.asarray(jax.device_get(out))
    dt = _time.perf_counter() - t0
    p_len = toks.shape[1]
    click.echo(_json.dumps({
        "model": model_name,
        "tokens": out.tolist(),
        "new_tokens": out[:, p_len:].tolist(),
        "wall_s": round(dt, 3),
        "tok_per_sec": round(b * max_new_tokens / dt, 1),
        "backend": jax.default_backend(),
        **({"draft_model": draft_model, "spec_k": spec_k}
           if draft_model else {}),
        **({"int8_weights": True} if int8_weights else {}),
        **({"int8_kv": True} if int8_kv else {}),
        **({"kv_ring": True} if kv_ring else {}),
    }))


@cli.command()
@click.option("--model", "model_name", required=True)
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8000, type=int)
@click.option("--checkpoint", default=None, type=click.Path())
@click.option("--int8-weights", is_flag=True, default=False)
@click.option("--int8-kv", is_flag=True, default=False)
@click.option("--kv-ring", is_flag=True, default=False,
              help="O(window) ring KV cache (sliding-window models).")
@click.option("--kv-ring-slack", default=0, type=int,
              help="Spare ring slots beyond the window; speculative "
                   "requests need >= spec_k - 1 (default 0 rejects "
                   "them).")
@click.option("--prefix-cache", default=4, type=int,
              help="Prefix-cache entries (POST /prefill registers a "
                   "system prompt; matching /generate requests skip "
                   "its prefill). 0 disables; each entry holds a full "
                   "KV cache on device.")
@click.option("--max-batch", default=8, type=int)
@click.option("--batching", default="continuous",
              type=click.Choice(["continuous", "coalesce", "off"]),
              help="Batching policy: continuous (slot-based engine "
                   "serving greedy AND sampled requests, default), "
                   "coalesce (legacy whole-request merging of greedy "
                   "traffic; sampled decodes solo), off (serialize).")
@click.option("--slots", "n_slots", default=8, type=int,
              help="Continuous-batching decode slots (physical batch "
                   "width; KV memory = slots x one request cache).")
@click.option("--queue-depth", default=64, type=int,
              help="Admission-queue bound (rows); a full queue "
                   "returns 429 + Retry-After.")
@click.option("--prefill-chunk", default=None, type=int,
              help="Default interleaved-prefill chunk (tokens); long "
                   "prompts prefill one chunk per decode boundary.")
@click.option("--decode-window", default=8, type=int,
              help="Max decode steps fused per device dispatch when "
                   "no admission could happen sooner (the engine "
                   "drops to single steps under admission pressure).")
@click.option("--mesh", "mesh_arg", default=None,
              help="Serve over a device mesh, e.g. 'tp=4' or "
                   "'tp=2,ep=2': params go under NamedSharding and "
                   "the slot KV cache shards its heads axis over tp "
                   "(experts over ep; dp shards the slot axis on "
                   "fixed-lane pools).  The exact serving layout — "
                   "meshed responses are token-bitwise-identical to "
                   "unmeshed ones per seed.  Requires --batching "
                   "continuous and dp*tp*ep local devices.")
@click.option("--kv-paged", is_flag=True, default=False,
              help="Paged KV cache: slot KV lives in a pool of "
                   "fixed-size pages with per-slot page tables and "
                   "copy-on-write shared-prefix pages, so occupancy "
                   "is bounded by token usage instead of slots x "
                   "max_position lanes (continuous batching, "
                   "plain/int8 caches only).")
@click.option("--kv-page-tokens", default=64, type=int,
              help="With --kv-paged: positions per KV page "
                   "(>= 8; smaller pages pack tighter, bigger pages "
                   "gather/scatter less).")
@click.option("--kv-pages", default=None, type=int,
              help="With --kv-paged: page-pool size in pages "
                   "(default: the fixed-lane footprint, slots x "
                   "ceil(max_position / page size) — same memory, "
                   "paged layout).")
@click.option("--kv-lazy", is_flag=True, default=False,
              help="With --kv-paged: LAZY page reservation — "
                   "admission reserves prompt + one decode window "
                   "instead of the full budget, slots grow their "
                   "page tables at step boundaries, and pool "
                   "exhaustion preempts the resident with the most "
                   "remaining budget (token-identical resume).  "
                   "Packs more residents when outputs run short of "
                   "budget.")
@click.option("--kv-host-spill-bytes", default=0, type=int,
              help="With --kv-paged: host-RAM byte budget for the "
                   "prefix store's SPILL tier — entries evicted from "
                   "device pages under pressure spill their payloads "
                   "to host buffers instead of dropping; a hit "
                   "re-materializes via device_put (and promotes "
                   "back to pages when the pool has room).  0 "
                   "(default) keeps the drop-on-evict behavior.")
@click.option("--prefix-fetch/--no-prefix-fetch", default=False,
              help="With --kv-paged and --kv-host-spill-bytes: arm "
                   "the FLEET prefix tier's wire-fetch client — a "
                   "local prefix miss carrying a router hint "
                   "({\"prefix_hint\": ...}) fetches the holder's "
                   "spilled payload over HTTP (checksummed; any "
                   "failure degrades to re-prefill, counted in "
                   "prefix_fetch_failed_total).  The SERVING half "
                   "(/prefix/fetch|ingest|handoff|evict, GET "
                   "/prefix/index) is always mounted on paged "
                   "servers.")
@click.option("--prefix-fetch-timeout", default=5.0, type=float,
              help="Per-connection timeout (seconds) for wire "
                   "fetches and handoff pushes.")
@click.option("--prefix-fetch-min-tokens", default=16, type=int,
              help="Fetch-policy floor: prefixes shorter than this "
                   "re-prefill locally (wire RTT beats tiny "
                   "prefills).")
@click.option("--prefix-fetch-remat-ratio", default=0.26, type=float,
              help="Fetch-policy curve: rematerialization cost as a "
                   "fraction of re-prefill cost (the measured "
                   "spilled-hit ratio; docs/SERVING.md).")
@click.option("--role", default="both",
              type=click.Choice(["prefill", "decode", "both"]),
              help="Disaggregated-serving role (docs/SERVING.md "
                   "\"Disaggregated serving\"). 'both' (default) is "
                   "today's monolithic replica, byte-for-byte. "
                   "'prefill' runs prompt prefill only — serves "
                   "/prefill and the /prefix/* wire lanes, rejects "
                   "/generate (400), no decode residents; needs "
                   "--kv-paged and --kv-host-spill-bytes. 'decode' "
                   "pulls handed-off KV over the wire-fetch lane; "
                   "needs --prefix-fetch. The router learns roles "
                   "from /healthz and schedules prefill->decode as "
                   "a two-stage attempt.")
@click.option("--default-priority", default="interactive",
              type=click.Choice(["interactive", "batch"]),
              help="Priority class for requests that don't declare "
                   "one ({\"priority\": ...}): interactive drains "
                   "ahead of batch, and batch decodes are "
                   "preemptible under --slo-ttft-ms.")
@click.option("--batch-queue-depth", default=None, type=int,
              help="Admission-queue bound (rows) for the BATCH "
                   "class (default: --queue-depth; the interactive "
                   "class always uses --queue-depth).")
@click.option("--queue-deadline-ms", default=None, type=int,
              help="Shed an INTERACTIVE request (503 + reason "
                   "queue_deadline) that got zero engine attention "
                   "for this long — it could not start before its "
                   "deadline, so don't let it rot in the queue.")
@click.option("--batch-queue-deadline-ms", default=None, type=int,
              help="Same shed deadline for the BATCH class queue.")
@click.option("--slo-ttft-ms", default=None, type=int,
              help="Interactive TTFT SLO target: when the "
                   "interactive class's admission-anchored TTFT p99 "
                   "(or the waiting head's own age) degrades past "
                   "this, the scheduler preempts the longest batch "
                   "decode and requeues it with its "
                   "generated-so-far prefix (token-identical "
                   "resume). Unset = never preempt.")
@click.option("--request-timeout", default=600.0, type=float,
              help="Bounded front-end wait (seconds) for "
                   "engine-path requests: one with no terminal "
                   "state after this long is shed with 503 + reason "
                   "request_timeout instead of holding its HTTP "
                   "worker until engine drain. Solo/coalesce paths "
                   "bound waits via deadline checks at their "
                   "dispatch boundaries.")
@click.option("--draft-model", "--spec-draft", "draft_model",
              default=None,
              help="Zoo model enabling SPECULATIVE requests "
                   "({\"speculative\": true}); same vocab as --model "
                   "(--spec-draft is an alias). With the default "
                   "--batching continuous, speculative requests ride "
                   "the engine's slot pool.")
@click.option("--draft-checkpoint", default=None, type=click.Path())
@click.option("--spec-k", default=4, type=int,
              help="Default draft proposals per speculative round "
                   "for requests that don't pass spec_k — and the "
                   "engine's cap: requests asking for more decode "
                   "solo.")
@click.option("--trace-buffer", default=4096, type=int,
              help="Telemetry ring capacity in trace events (request "
                   "lifecycle spans + engine step records, exported "
                   "by GET /trace as Chrome trace JSON). 0 disables "
                   "span recording; /metrics histograms stay live.")
@click.option("--trace-file", default=None, type=click.Path(),
              help="Dump the telemetry ring to this JSONL file on "
                   "shutdown (one trace event per line).")
@click.option("--profile-dir", default=None, type=click.Path(),
              help="Enable POST /profile/start|stop: jax.profiler "
                   "device traces land in timestamped subdirs here "
                   "(omit to keep the endpoints disabled).")
@click.option("--profile-every", default=0, type=int,
              help="FLIGHT RECORDER (needs --profile-dir): every N "
                   "decode dispatches, wrap --profile-steps step "
                   "boundaries in a jax.profiler window, auto-analyze "
                   "the dump, and publish trace-true attribution — "
                   "collective/host-gap/device-busy shares + serving "
                   "MFU — as /metrics gauges and GET /profile/report. "
                   "0 (default) disables.")
@click.option("--profile-steps", default=8, type=int,
              help="With --profile-every: decode dispatches per "
                   "recorder window.")
@click.option("--access-log", is_flag=True, default=False,
              help="One structured JSON line per request on stderr "
                   "(status, kind, rows, tokens, latency) — includes "
                   "failed requests, which are otherwise silent.")
@click.option("--sanitize", is_flag=True, default=False,
              help="Wrap the serving locks in the lock-order "
                   "sanitizer (analysis/locksan.py): raises on "
                   "lock-order inversion, reports in /info. Debug "
                   "aid — off by default (and off in benchmark "
                   "runs; see bench_serving_load.py --sanitize).")
@click.option("--sanitize-max-hold", default=None, type=float,
              help="With --sanitize: flag device_lock holds longer "
                   "than this many seconds (unset = no hold limit).")
@click.option("--sanitize-report", "sanitize_report", default=None,
              type=click.Path(),
              help="With --sanitize: write the observed lock "
                   "acquisition graph (the same dict /info reports) "
                   "to this JSON file at shutdown — the offline "
                   "input to the static-vs-runtime lock-graph "
                   "cross-check (docs/ANALYSIS.md).")
@click.option("--request-history", default=256, type=int,
              help="Terminal request-record retention ring behind "
                   "GET /requests/<id>: per-request causal timelines "
                   "(queue wait, admission slot, preemptions with "
                   "preemptor IDs, page waits, terminal cause), "
                   "newest N retained. 0 disables recording.")
@click.option("--stall-timeout", default=None, type=float,
              help="Arm the STALL WATCHDOG: when work exists but no "
                   "decode-step boundary completes for this many "
                   "seconds (or a queued request ages past 4x its "
                   "class queue deadline), write a one-shot "
                   "diagnostic bundle (--stall-dir) — state "
                   "snapshot, trace tail, thread stacks — and bump "
                   "ptpu_serving_stalls_total. Unset = off.")
@click.option("--stall-dir", default=".", type=click.Path(),
              help="With --stall-timeout: directory stall bundles "
                   "(stall_<n>_<pid>.json) are written to.")
@click.option("--forensics/--no-forensics", "forensics",
              default=True,
              help="Tail-latency forensics (docs/SERVING.md): the "
                   "per-request phase ledger, histogram exemplars, "
                   "and the anomaly sentry behind GET /anomalies. "
                   "ON by default (<=3% contract, bench-pinned); "
                   "--no-forensics reduces it all to attribute "
                   "checks.")
@click.option("--exemplar-k", default=4, type=int,
              help="Request-ID exemplars retained per latency "
                   "histogram bucket (OpenMetrics suffixes on "
                   "/metrics + GET /debug/exemplars). 0 disables "
                   "exemplars only.")
@click.option("--forensics-dir", default=None, type=click.Path(),
              help="Arm per-episode anomaly bundles: first "
                   "detection per episode writes "
                   "anomaly_<n>_<pid>.json (finding, state, the "
                   "flagged window's exemplar records, trace tail) "
                   "here. Unset = findings/counters only, no "
                   "bundles.")
@click.option("--fault-plan", "fault_plan_path", default=None,
              type=click.Path(exists=True),
              help="CHAOS TESTING: arm the deterministic seeded "
                   "fault-injection harness from a JSON plan "
                   "(serving/faults.py — sites: step/page_alloc/"
                   "slow_step/engine_death/prefix_store/"
                   "socket_reset/telemetry).  Injected faults "
                   "exercise the containment ladder: bounded step "
                   "retries, quarantine bisection (the poisoned "
                   "request alone fails 500 poisoned_request), "
                   "supervised engine restart with requeue-and-"
                   "resume, and the crash-storm circuit breaker. "
                   "Unset (default): zero probes armed.")
@click.option("--no-supervise", is_flag=True, default=False,
              help="Disable the engine crash supervisor (an engine "
                   "crash then fails every in-flight request "
                   "instead of restarting with token-identical "
                   "requeue-and-resume — the pre-crash-only "
                   "behavior; debugging aid).")
@click.option("--cpu", is_flag=True, default=False)
def serve(model_name, host, port, checkpoint, int8_weights, int8_kv,
          kv_ring, kv_ring_slack, prefix_cache, max_batch, batching,
          n_slots, queue_depth, prefill_chunk, decode_window,
          mesh_arg, kv_paged, kv_page_tokens, kv_pages,
          kv_lazy, kv_host_spill_bytes,
          prefix_fetch, prefix_fetch_timeout,
          prefix_fetch_min_tokens, prefix_fetch_remat_ratio,
          role,
          default_priority, batch_queue_depth, queue_deadline_ms,
          batch_queue_deadline_ms, slo_ttft_ms, request_timeout,
          draft_model, draft_checkpoint, spec_k, trace_buffer,
          trace_file, profile_dir, profile_every, profile_steps,
          access_log, sanitize, sanitize_max_hold, sanitize_report,
          request_history,
          stall_timeout, stall_dir, forensics, exemplar_k,
          forensics_dir, fault_plan_path, no_supervise,
          cpu):
    """Serve a zoo model over HTTP (/healthz, /info, /metrics,
    /generate, /prefill — the last registers a prompt prefix whose
    prefill later /generate requests skip; /trace exports the
    telemetry ring as Chrome trace JSON, and /profile/start|stop
    drives on-demand jax.profiler traces when --profile-dir is set).

    The reference's `V1Service` schedules an opaque serving container;
    here the framework ships the model server itself (stdlib HTTP, jit
    compile cache, int8 serving flags — see the serving package).

    Greedy AND sampled traffic runs through the continuous-batching
    engine by default: a fixed pool of decode slots with
    step-boundary admission, eos-eviction, interleaved chunked
    prefill, and 429 backpressure once the admission queue fills
    (--batching selects the legacy coalescing or serialized baselines
    for A/Bs).  Sampled slots draw from position-keyed PRNG streams —
    a request's tokens depend on its (seed, token index) only, never
    on what else shares the pool — so responses are reproducible
    under any concurrency.  Beam/speculative requests decode solo.

    Requests are cancellable, deadline-bearing, and prioritized
    (docs/SERVING.md "Request lifecycle"): client disconnects and
    {"deadline_ms": N} expiries evict their slots at the next step
    boundary; {"priority": "interactive"|"batch"} picks the class
    queue; --slo-ttft-ms arms batch preemption with token-identical
    resume; per-class queue deadlines shed unstartable requests with
    503; and POST /drain stops admission, finishes in-flight work,
    and turns /healthz readiness off.
    """
    import jax

    if cpu:
        jax.config.update("jax_platforms", "cpu")
    from polyaxon_tpu.serving import (ModelServer,
                                      PrefixFetchPolicy,
                                      make_server)

    if draft_checkpoint and not draft_model:
        # pre-checkable usage error: fail before paying the full
        # target build (checkpoint restore can take minutes)
        raise click.ClickException(
            "--draft-checkpoint requires --draft-model")
    if trace_buffer < 0:
        # same fail-fast contract: no model build for a bad flag
        raise click.ClickException("--trace-buffer must be >= 0")
    if profile_every < 0:
        raise click.ClickException("--profile-every must be >= 0")
    if profile_steps < 1:
        raise click.ClickException("--profile-steps must be >= 1")
    if profile_every and not profile_dir:
        raise click.ClickException(
            "--profile-every needs --profile-dir (the flight "
            "recorder writes jax.profiler windows there)")
    if profile_every and batching != "continuous":
        raise click.ClickException(
            "--profile-every requires --batching continuous (the "
            "flight recorder windows decode-step boundaries)")
    if sanitize_max_hold is not None and not sanitize:
        raise click.ClickException(
            "--sanitize-max-hold requires --sanitize")
    if sanitize_report is not None and not sanitize:
        raise click.ClickException(
            "--sanitize-report requires --sanitize")
    if request_history < 0:
        raise click.ClickException("--request-history must be >= 0")
    if stall_timeout is not None and stall_timeout <= 0:
        raise click.ClickException("--stall-timeout must be > 0")
    if stall_timeout is not None and batching != "continuous":
        raise click.ClickException(
            "--stall-timeout requires --batching continuous (the "
            "watchdog monitors decode-step boundaries)")
    fault_plan = None
    if fault_plan_path is not None:
        # Parse + validate the plan BEFORE the model build (the
        # fail-fast contract): a typo'd fault site must not cost a
        # checkpoint restore.
        from polyaxon_tpu.serving import FaultPlan

        try:
            fault_plan = FaultPlan.load(fault_plan_path)
        except (ValueError, OSError) as e:
            raise click.ClickException(
                f"--fault-plan {fault_plan_path}: {e}")
    for name, v in (("--queue-deadline-ms", queue_deadline_ms),
                    ("--batch-queue-deadline-ms",
                     batch_queue_deadline_ms),
                    ("--slo-ttft-ms", slo_ttft_ms)):
        if v is not None and v < 1:
            raise click.ClickException(f"{name} must be >= 1")
    if request_timeout is not None and request_timeout <= 0:
        raise click.ClickException("--request-timeout must be > 0")
    # Paged-KV flag validation: fail fast, before the model build.
    if kv_page_tokens < 8:
        raise click.ClickException("--kv-page-tokens must be >= 8")
    if kv_pages is not None and kv_pages < 1:
        raise click.ClickException("--kv-pages must be >= 1")
    if kv_paged and kv_ring:
        raise click.ClickException(
            "--kv-paged needs a plain/int8 max_position cache; it "
            "cannot combine with --kv-ring (the ring is already "
            "O(window))")
    if kv_paged and batching != "continuous":
        raise click.ClickException(
            "--kv-paged requires --batching continuous (paging is "
            "the engine's slot storage)")
    if kv_lazy and not kv_paged:
        raise click.ClickException(
            "--kv-lazy requires --kv-paged (lazy growth is a page-"
            "reservation policy)")
    if kv_host_spill_bytes < 0:
        raise click.ClickException(
            "--kv-host-spill-bytes must be >= 0")
    if kv_host_spill_bytes and not kv_paged:
        raise click.ClickException(
            "--kv-host-spill-bytes requires --kv-paged (the host "
            "tier spills page-pool payloads)")
    if prefix_fetch and not (kv_paged and kv_host_spill_bytes):
        raise click.ClickException(
            "--prefix-fetch requires --kv-paged and "
            "--kv-host-spill-bytes (wire-fetched payloads admit "
            "through the host spill tier)")
    # Role validation BEFORE the model build (fail-fast contract) —
    # mirror the ModelServer checks so a mis-flagged tier dies on
    # usage, not after a checkpoint restore.
    if role == "prefill" and not (kv_paged and kv_host_spill_bytes):
        raise click.ClickException(
            "--role prefill requires --kv-paged and "
            "--kv-host-spill-bytes (a prefill tier's product is "
            "admit-ready KV served over the /prefix/fetch lane)")
    if role == "decode" and not prefix_fetch:
        raise click.ClickException(
            "--role decode requires --prefix-fetch (the decode tier "
            "admits handed-off prefills through the wire-fetch "
            "lane)")
    mesh_spec = None
    if mesh_arg is not None:
        # Parse BEFORE the model build (fail-fast contract): a typo'd
        # axis or a size the local device count can't honor must not
        # cost a checkpoint restore.  Device-count validation happens
        # in ServingMesh (after `--cpu` had its chance to switch the
        # platform), but the spec grammar is checkable now.
        if batching != "continuous":
            raise click.ClickException(
                "--mesh requires --batching continuous (the mesh "
                "shards the engine's slot KV pools)")
        from polyaxon_tpu.serving.meshed import MeshError, parse_mesh

        try:
            mesh_spec = parse_mesh(mesh_arg)
        except MeshError as e:
            raise click.ClickException(str(e))
    try:
        # Shared validation with the server/library (_check_spec_k):
        # one message for a bad --spec-k on every surface.
        from polyaxon_tpu.models.generate import _check_spec_k

        _check_spec_k(spec_k)
    except ValueError as e:
        raise click.ClickException(str(e))
    model, variables = _build_serving_model(
        model_name, 1, checkpoint, int8_kv, int8_weights,
        kv_ring=kv_ring, kv_ring_slack=kv_ring_slack)
    draft = draft_vars = None
    if draft_model:
        # The draft mirrors the target's cache mode: a standard-cache
        # draft would re-impose the max_position bound --kv-ring
        # exists to lift.
        draft, draft_vars = _build_serving_model(
            draft_model, 1, draft_checkpoint, int8_kv, int8_weights,
            kv_ring=kv_ring, kv_ring_slack=kv_ring_slack)
    from polyaxon_tpu.serving.meshed import MeshError

    try:
        ms = ModelServer(model, variables, model_name=model_name,
                         max_batch=max_batch, batching=batching,
                         n_slots=n_slots, queue_depth=queue_depth,
                         prefill_chunk=prefill_chunk,
                         decode_window=decode_window,
                         mesh=mesh_spec,
                         kv_paged=kv_paged,
                         kv_page_tokens=kv_page_tokens,
                         kv_pages=kv_pages,
                         kv_lazy=kv_lazy,
                         kv_host_spill_bytes=kv_host_spill_bytes,
                         prefix_fetch=prefix_fetch,
                         prefix_fetch_policy=PrefixFetchPolicy(
                             min_tokens=prefix_fetch_min_tokens,
                             remat_ratio=prefix_fetch_remat_ratio)
                         if prefix_fetch else None,
                         prefix_fetch_timeout_s=prefix_fetch_timeout,
                         role=role,
                         default_priority=default_priority,
                         batch_queue_depth=batch_queue_depth,
                         queue_deadline_s=queue_deadline_ms / 1e3
                         if queue_deadline_ms is not None else None,
                         batch_queue_deadline_s=batch_queue_deadline_ms
                         / 1e3 if batch_queue_deadline_ms is not None
                         else None,
                         slo_ttft_s=slo_ttft_ms / 1e3
                         if slo_ttft_ms is not None else None,
                         request_timeout_s=request_timeout,
                         prefix_cache=prefix_cache,
                         draft_model=draft, draft_variables=draft_vars,
                         spec_k=spec_k,
                         trace_buffer=trace_buffer,
                         profile_dir=profile_dir,
                         profile_every=profile_every,
                         profile_steps=profile_steps,
                         access_log=access_log,
                         sanitize=sanitize,
                         sanitize_max_hold_s=sanitize_max_hold,
                         sanitize_report=sanitize_report,
                         request_history=request_history,
                         stall_timeout_s=stall_timeout,
                         stall_dir=stall_dir,
                         forensics=forensics,
                         exemplar_k=exemplar_k,
                         forensics_dir=forensics_dir,
                         fault_plan=fault_plan,
                         supervise=not no_supervise,
                         info={**({"int8_weights": True}
                                  if int8_weights else {}),
                               **({"int8_kv": True} if int8_kv else {}),
                               **({"kv_ring": True} if kv_ring else {}),
                               **({"kv_page_tokens": kv_page_tokens}
                                  if kv_paged else {}),
                               **({"kv_lazy_mode": True}
                                  if kv_lazy else {}),
                               **({"draft_model": draft_model}
                                  if draft_model else {})})
    except MeshError as e:
        # Mesh validation (device count, head/expert divisibility)
        # fails AFTER the model build by necessity — it needs the
        # model config — but still deserves the clean usage-error
        # surface.
        raise click.ClickException(str(e))
    try:
        srv = make_server(host, port, ms)
    except OSError as e:
        raise click.ClickException(
            f"cannot bind {host}:{port}: {e}")
    click.echo(f"serving {model_name} on http://{host}:"
               f"{srv.server_address[1]}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
    finally:
        ms.close()
        if trace_file:
            # Shutdown span dump, through the tracking stack's async
            # writer (telemetry.dump_spans_jsonl) — the offline twin
            # of GET /trace for post-mortem trace_report.py analysis.
            from polyaxon_tpu.serving.telemetry import \
                dump_spans_jsonl

            n = dump_spans_jsonl(ms.telemetry, trace_file)
            click.echo(f"wrote {n} trace events to {trace_file}",
                       err=True)


@cli.command()
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8100, type=int)
@click.option("--replica", "replicas", multiple=True, required=True,
              help="Replica endpoint (host:port or http://host:port);"
                   " repeat per replica.")
@click.option("--probe-interval", default=0.5, type=float,
              help="Seconds between /healthz probe rounds.")
@click.option("--probe-timeout", default=2.0, type=float,
              help="Per-probe socket timeout (a timeout-less probe "
                   "is how a hung replica wedges the router).")
@click.option("--down-after", default=2, type=int,
              help="Consecutive transport failures that trip a "
                   "replica out of rotation.")
@click.option("--cooldown", default=1.0, type=float,
              help="Seconds out of rotation before the half-open "
                   "re-admission probe.")
@click.option("--retry-ratio", default=0.1, type=float,
              help="Retry-budget refill per live request (retries + "
                   "hedges can never exceed this fraction of "
                   "traffic plus --retry-burst).")
@click.option("--retry-burst", default=8.0, type=float,
              help="Retry-budget bucket capacity (the cold-start "
                   "failover headroom).")
@click.option("--max-attempts", default=3, type=int,
              help="Replica attempts per request (first + "
                   "failovers).")
@click.option("--request-timeout", default=120.0, type=float,
              help="Per-attempt read timeout / default request "
                   "deadline, seconds.")
@click.option("--hedge", default="off",
              help="'off', 'p99' (duplicate a request sitting past "
                   "the sliding p99 watermark), or a fixed "
                   "threshold in seconds.")
@click.option("--hedge-min", default=0.2, type=float,
              help="Hedge watermark floor, seconds.")
@click.option("--affinity/--no-affinity", default=True,
              help="Radix-prefix affinity: route a request to the "
                   "replica whose store holds its registered "
                   "prefix (never beats health).")
@click.option("--prefix-handoff/--no-prefix-handoff", default=True,
              help="Drain-time cache migration: a rolling restart "
                   "pushes the drainee's hot host-tier prefix "
                   "entries to its router-chosen successor (POST "
                   "/prefix/handoff) before the flush.  Off = a "
                   "restart is a cache flush (the per-replica "
                   "baseline).")
@click.option("--disagg-min-tokens", default=16, type=int,
              help="Disaggregated serving: prompts at or above this "
                   "length take the two-stage prefill->decode "
                   "schedule when the fleet runs a dedicated "
                   "--role prefill tier (shorter prompts decode "
                   "locally — the handoff would cost more than the "
                   "prefill).")
@click.option("--rebalance-every", default=0.0, type=float,
              help="Seconds between cadenced POST "
                   "/fleet/prefix/rebalance passes, driven off the "
                   "federated kv_host_* gauges (runs only while "
                   ">=2 replicas hold host-tier entries; "
                   "one-flight; failures counted, never fatal).  "
                   "0 = operator trigger only (default).")
@click.option("--min-ready", default=1, type=int,
              help="Rolling restart never drops the ready-replica "
                   "count below this.")
@click.option("--fleet-fault-plan", default=None, type=click.Path(),
              help="Seeded fleet chaos plan (JSON; replica_kill/"
                   "replica_hang/replica_slow sites) — local "
                   "replicas only.")
@click.option("--request-history", default=256, type=int,
              help="Router-side request-span retention ring "
                   "(GET /fleet/requests/<id> — the cross-replica "
                   "stitched timeline); 0 disables.")
@click.option("--slo", default=None,
              help="Declared objectives evaluated over a sliding "
                   "window of the router's own accounting, e.g. "
                   "'availability=99.9,ttft_p99_ms=1000'; exported "
                   "as ptpu_router_slo_burn_rate{objective=}.")
@click.option("--slo-window", default=512, type=int,
              help="Sliding-window size (requests) the SLO burn "
                   "rates are computed over.")
@click.option("--forensics/--no-forensics", "forensics",
              default=True,
              help="Router-side tail-latency forensics: the "
                   "per-request router phase ledger (route_pick/"
                   "replica_attempt/prefill_remote/retry_backoff), "
                   "its anomaly sentry (GET /anomalies), and the "
                   "fleet-merged GET /fleet/anomalies ranking.")
@click.option("--forensics-dir", default=None, type=click.Path(),
              help="Arm per-episode router anomaly bundles "
                   "(anomaly_<n>_<pid>.json). Unset = findings/"
                   "counters only.")
def route(host, port, replicas, probe_interval, probe_timeout,
          down_after, cooldown, retry_ratio, retry_burst,
          max_attempts, request_timeout, hedge, hedge_min, affinity,
          prefix_handoff, disagg_min_tokens, rebalance_every,
          min_ready, fleet_fault_plan,
          request_history, slo, slo_window, forensics,
          forensics_dir):
    """Run the replica ROUTER tier in front of N `ptpu serve`
    replicas (docs/SERVING.md "Fleet").

    The router probes each replica's /healthz (503 draining/
    engine_down takes it out of rotation; recovery re-admits it
    after a half-open success probe), balances by least-outstanding
    with radix-prefix affinity, fails replica deaths over inside a
    bounded retry budget with jittered backoff, optionally hedges
    requests past the p99 watermark (first winner cancels the
    loser), and rolls restarts via POST /fleet/restart without
    dropping below --min-ready ready replicas.

    Fleet observability (docs/SERVING.md "Fleet observability"):
    GET /fleet/requests/<id> stitches the router's request spans
    with every involved replica's history record into one causal
    timeline; GET /fleet/metrics federates every replica's /metrics
    with replica= labels and fleet rollups; --slo arms router-side
    error-budget burn-rate gauges.
    """
    from polyaxon_tpu.serving import (ReplicaRouter,
                                      make_router_server)

    try:
        router = ReplicaRouter(
            list(replicas),
            probe_interval_s=probe_interval,
            probe_timeout_s=probe_timeout,
            down_after=down_after,
            cooldown_s=cooldown,
            retry_ratio=retry_ratio,
            retry_burst=retry_burst,
            max_attempts=max_attempts,
            request_timeout_s=request_timeout,
            hedge=hedge,
            hedge_min_s=hedge_min,
            affinity=affinity,
            prefix_handoff=prefix_handoff,
            disagg_min_tokens=disagg_min_tokens,
            rebalance_every_s=rebalance_every,
            min_ready=min_ready,
            fleet_faults=fleet_fault_plan,
            request_history=request_history,
            slo=slo,
            slo_window=slo_window,
            forensics=forensics,
            forensics_dir=forensics_dir)
    except ValueError as e:
        raise click.ClickException(str(e))
    try:
        srv = make_router_server(host, port, router)
    except OSError as e:
        router.close()
        raise click.ClickException(
            f"cannot bind {host}:{port}: {e}")
    click.echo(f"routing {len(replicas)} replica(s) on "
               f"http://{host}:{srv.server_address[1]}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
    finally:
        router.close()


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------


@cli.group()
def ops():
    """Inspect and manage runs."""


def _store():
    from polyaxon_tpu.client.run_client import get_client

    return get_client()


@ops.command(name="ls")
@click.option("--project", default=None)
@click.option("--query", "-q", default=None,
              help='Filter, e.g. "status:running, metrics.loss:<0.1".')
@click.option("--sort", default="-created_at")
@click.option("--limit", default=20, type=int)
@click.option("--offset", default=0, type=int)
def ops_ls(project, query, sort, limit, offset):
    """List runs."""
    from polyaxon_tpu.client.store import StoreError
    from polyaxon_tpu.query import QueryError

    try:
        runs = _store().list_runs(project=project, query=query, sort=sort,
                                  limit=limit, offset=offset)
    except (QueryError, StoreError) as e:
        raise click.ClickException(str(e))
    if not runs:
        click.echo("No runs found.")
        return
    fmt = "{:<14} {:<24} {:<12} {:<11} {:<12} {:>3} {:>9}"
    click.echo(fmt.format("UUID", "NAME", "KIND", "STATUS", "QUEUE",
                          "PRI", "DURATION"))
    for r in runs:
        dur = r.get("duration")
        click.echo(fmt.format(
            r["uuid"], (r.get("name") or "")[:24], str(r.get("kind") or "-"),
            r.get("status") or "-", (r.get("queue") or "-")[:12],
            str(r.get("priority") or 0), f"{dur:.1f}s" if dur else "-",
        ))


@ops.command(name="get")
@click.argument("run_uuid")
def ops_get(run_uuid):
    """Show one run's record (+ heartbeat age for running runs)."""
    record = _get_run_or_fail(run_uuid)
    if record.get("status") == "running":
        try:
            beat = _store().heartbeat_at(run_uuid)
        except Exception:  # noqa: BLE001 - informational only
            beat = None
        if beat is not None:
            import time as _time

            # Clamp: in API mode `beat` is the server's clock; a few
            # seconds of client/server skew must not print a negative
            # age.
            record = {**record,
                      "heartbeat_age_s":
                          max(0.0, round(_time.time() - beat, 1))}
    click.echo(json.dumps(record, indent=2, default=str))


def _get_run_or_fail(run_uuid: str) -> Dict[str, Any]:
    from polyaxon_tpu.client.store import StoreError

    try:
        return _store().get_run(run_uuid)
    except StoreError as e:
        raise click.ClickException(str(e))


@ops.command(name="compare")
@click.argument("run_uuids", nargs=-1, required=True)
def ops_compare(run_uuids):
    """Compare runs side by side: status, inputs, last metrics."""
    from polyaxon_tpu.client.store import StoreError

    store = _store()
    records, metrics = [], []
    for u in run_uuids:
        try:
            records.append(store.get_run(u))
        except StoreError as e:
            raise click.ClickException(str(e))
        try:
            metrics.append(store.last_metrics(u))
        except Exception:  # noqa: BLE001 - missing metrics show as '-'
            metrics.append({})

    def fmt(value):
        return f"{value:.6g}" if isinstance(value, float) else str(value)

    input_keys = sorted({k for r in records
                         for k in (r.get("inputs") or {})})
    metric_keys = sorted({k for m in metrics for k in m})
    rows: List[Tuple[str, List[str]]] = [
        ("status", [r.get("status") or "-" for r in records]),
        ("duration", [f"{r['duration']:.1f}s" if r.get("duration")
                      else "-" for r in records]),
    ]
    rows += [(f"in:{k}", [fmt((r.get("inputs") or {}).get(k, "-"))
                          for r in records]) for k in input_keys]
    rows += [(f"metric:{k}", [fmt(m.get(k, "-")) for m in metrics])
             for k in metric_keys]

    label_w = max(16, max(len(k) for k, _ in rows) + 1)
    width = 22
    header = " ".join(f"{u[:12]:>{width}}" for u in run_uuids)
    click.echo(f"{'':<{label_w}}{header}")
    for key, values in rows:
        cells = " ".join(f"{v:>{width}}" for v in values)
        click.echo(f"{key:<{label_w}}{cells}")


@ops.command(name="logs")
@click.argument("run_uuid")
@click.option("--replica", default=None)
@click.option("--tail", default=None, type=int)
@click.option("--follow", "-f", is_flag=True, default=False,
              help="Stream new log lines until the run finishes.")
def ops_logs(run_uuid, replica, tail, follow):
    """Print (or follow) a run's logs."""
    import time as _time

    from polyaxon_tpu.lifecycle import is_done
    from polyaxon_tpu.scheduler.api import ControlPlane

    _get_run_or_fail(run_uuid)
    store = _store()
    if not follow:
        click.echo(store.read_logs(run_uuid, replica=replica, tail=tail))
        return
    # Per-replica offset streaming (offsets are per file, so multiple
    # replicas can't shift each other's positions).  API store speaks
    # the protocol natively; the file store goes through an in-process
    # ControlPlane shim.
    reader = store if hasattr(store, "read_logs_multi") else \
        ControlPlane(store)
    offsets: Dict[str, int] = {}

    def drain() -> None:
        out = reader.read_logs_multi(run_uuid, offsets)
        replicas = out.get("replicas", {})
        many = len(replicas) > 1 or (replica is None and len(offsets) > 1)
        for rep in sorted(replicas):
            if replica is not None and rep != replica:
                offsets[rep] = replicas[rep]["offset"]
                continue
            chunk = replicas[rep]["logs"]
            offsets[rep] = replicas[rep]["offset"]
            if not chunk:
                continue
            if many:
                for line in chunk.splitlines():
                    click.echo(f"[{rep}] {line}")
            else:
                click.echo(chunk, nl=False)

    while True:
        drain()
        status = store.get_run(run_uuid).get("status")
        if is_done(status):
            drain()  # final read: lines flushed just before completion
            break
        _time.sleep(1.0)


@ops.command(name="statuses")
@click.argument("run_uuid")
def ops_statuses(run_uuid):
    """Print a run's status history."""
    _get_run_or_fail(run_uuid)
    for c in _store().get_statuses(run_uuid):
        line = f"{c.last_transition_time:.0f}  {c.type:<16} {c.reason or ''}"
        if c.message:
            line += f"  {c.message}"
        click.echo(line)


@ops.command(name="artifacts")
@click.argument("run_uuid")
def ops_artifacts(run_uuid):
    """List a run's artifact tree and lineage."""
    _get_run_or_fail(run_uuid)
    store = _store()
    root = store.artifacts_path(run_uuid)
    for dirpath, _, files in os.walk(root):
        for fname in files:
            path = os.path.join(dirpath, fname)
            click.echo(os.path.relpath(path, root))
    lineage = store.get_lineage(run_uuid)
    if lineage:
        click.echo("--- lineage ---")
        for rec in lineage:
            click.echo(f"{rec.get('kind'):<10} {rec.get('name')}")


@ops.command(name="metrics")
@click.argument("run_uuid")
@click.option("--name", default=None, help="One metric series (else last values).")
def ops_metrics(run_uuid, name):
    """Show tracked metrics."""
    _get_run_or_fail(run_uuid)
    store = _store()
    if name:
        for e in store.read_events(run_uuid, "metric", name):
            click.echo(f"step={e.get('step')} value={e.get('value')}")
    else:
        for metric, value in sorted(store.last_metrics(run_uuid).items()):
            click.echo(f"{metric}: {value}")


def _reap_local_service(store, run_uuid: str) -> bool:
    """Kill a locally-spawned service (runner.local._run_service
    records its pid/session in meta_info) and mark it stopped.  The
    k8s path doesn't need this — the operator reconciles STOPPING —
    but a local detached service has no operator watching it."""
    try:
        rec = store.get_run(run_uuid)
    except Exception:
        return False
    svc = (rec.get("meta_info") or {}).get("service") or {}
    pid = svc.get("pid")
    if not pid or svc.get("host") not in (None, "127.0.0.1"):
        return False
    import signal

    try:
        os.killpg(int(pid), signal.SIGTERM)
    except ProcessLookupError:
        pass  # already gone — marking stopped is correct
    except PermissionError:
        # We could NOT signal it (pid reuse across uids, etc.) —
        # claiming "stopped" would strand a live orphan with a
        # terminal-status record no second `ops stop` can fix.
        click.echo(f"cannot signal service pid {pid} "
                   f"(permission denied); not marking stopped",
                   err=True)
        return False
    store.set_status(run_uuid, "stopped", reason="CliStop", force=True)
    return True


@ops.command(name="stop")
@click.argument("run_uuid")
def ops_stop(run_uuid):
    """Request a run stop."""
    _get_run_or_fail(run_uuid)
    store = _store()
    ok = store.set_status(run_uuid, "stopping", reason="CliStop")
    if ok and _reap_local_service(store, run_uuid):
        click.echo("stopped (local service reaped)")
        return
    click.echo("stopping" if ok else "run is already done")


@ops.command(name="delete")
@click.argument("run_uuid")
@click.confirmation_option(prompt="Delete this run and its artifacts?")
def ops_delete(run_uuid):
    """Delete a run."""
    _get_run_or_fail(run_uuid)
    _store().delete_run(run_uuid)
    click.echo(f"deleted {run_uuid}")


@ops.command(name="restart")
@click.argument("run_uuid")
@click.option("--copy", "copy_artifacts", is_flag=True,
              help="Copy the original run's artifacts into the new run.")
def ops_restart(run_uuid, copy_artifacts):
    """Restart a run as a new run (optionally copying artifacts)."""
    record = _restart(run_uuid, copy_artifacts=copy_artifacts, resume=False)
    _echo_record(record)


@ops.command(name="resume")
@click.argument("run_uuid")
def ops_resume(run_uuid):
    """Resume a run: restart pointing at the SAME artifacts (latest
    checkpoint is picked up via {{ globals.run_artifacts_path }})."""
    record = _restart(run_uuid, copy_artifacts=True, resume=True)
    _echo_record(record)


def _restart(run_uuid: str, copy_artifacts: bool, resume: bool):
    import shutil

    from polyaxon_tpu.flow import V1Operation
    from polyaxon_tpu.runner import LocalExecutor

    record = _get_run_or_fail(run_uuid)
    content = record.get("content")
    if not content:
        raise click.ClickException(
            f"Run {run_uuid} stores no operation content; cannot restart")
    op = V1Operation.from_dict(content)
    # Sweep children were created with matrix stripped and their concrete
    # suggestion stored in meta_info — replay it.
    matrix_values = (record.get("meta_info") or {}).get("matrix_values")
    meta = {"restarted_from": run_uuid, "is_resume": resume}
    if matrix_values:
        meta["matrix_values"] = matrix_values

    if os.environ.get("POLYAXON_TPU_HOST"):
        # API mode: resubmit to the control plane; the agent executes.
        store = _store()
        new = store.create_run(
            name=record.get("name"), project=record.get("project"),
            content=content, kind=record.get("kind"), meta_info=meta,
            managed_by="agent",
            # keep queue routing/priority: a restarted tpu-v5e run must
            # stay claimable by queue-scoped agents
            queue=record.get("queue"),
            priority=record.get("priority") or 0,
        )
        store.set_status(new["uuid"], "queued", reason="CliRestart",
                         force=True)
        return store.get_run(new["uuid"])

    executor = LocalExecutor(project=record.get("project") or "default")
    new_uuid = executor.create_run(op, meta_info=meta)
    if copy_artifacts:
        src = executor.store.artifacts_path(run_uuid)
        dst = executor.store.artifacts_path(new_uuid)
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
    try:
        return executor.run_operation(op, run_uuid=new_uuid,
                                      matrix_values=matrix_values)
    except Exception as e:
        raise click.ClickException(f"Restart failed: {e}")


# ---------------------------------------------------------------------------
# config / check / version
# ---------------------------------------------------------------------------


@cli.command()
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
@click.option("-f", "--file", "files", multiple=True,
              type=click.Path(),
              help="Validate polyaxonfile(s) instead of running the "
                   "static analyzer.")
@click.option("-P", "--param", "params", multiple=True)
@click.option("--format", "fmt", type=click.Choice(["text", "json"]),
              default="text", help="Finding output format.")
@click.option("--baseline", "baseline_path", default=None,
              type=click.Path(),
              help="Baseline file of accepted findings (default: the "
                   "committed polyaxon_tpu/analysis/baseline.json).")
@click.option("--update-baseline", is_flag=True, default=False,
              help="Rewrite the baseline from the current findings "
                   "(stable sort; justifications preserved, new "
                   "entries get a TODO placeholder to fill in).")
@click.option("--changed", "changed_ref", is_flag=False,
              flag_value="HEAD", metavar="[REF]",
              # No `default=`: click only treats the value as optional
              # (bare `--changed` -> flag_value) when the default is
              # left UNSET; passing default=None re-arms the
              # requires-an-argument parse.  The resolved default is
              # still None.
              help="Incremental mode: lint only files changed vs a "
                   "git ref (default HEAD), plus untracked files — "
                   "identical findings/exit semantics to a full run "
                   "on those files.  Fast enough for a pre-commit "
                   "hook.  Use --changed=REF when followed by PATHS "
                   "(a bare ref would swallow the next argument).")
@click.option("--dump-lock-graph", "lock_graph_path", default=None,
              type=click.Path(),
              help="Write the canonical static lock-order graph "
                   "(the committed analysis/lockorder.json artifact) "
                   "to this path and exit.")
def check(paths, files, params, fmt, baseline_path, update_baseline,
          changed_ref, lock_graph_path):
    """Validate a polyaxonfile (-f), or run the JAX-aware static
    analyzer over PATHS (default: polyaxon_tpu/).

    The analyzer machine-checks the serving stack's own invariants —
    per-module rule families RNG-DET, LOCK-HOLD, JIT-PURITY,
    HOST-SYNC, EXC-SWALLOW, ... plus the whole-program concurrency
    families LOCK-ORDER (static lock-acquisition-graph cycles =
    potential deadlocks, with witness paths) and THREAD-SHARE
    (attributes written from several thread roots with no common
    lock) — docs/ANALYSIS.md has the catalog.  Exit status is
    non-zero when findings exist beyond the committed baseline;
    suppress locally-justified findings with `# ptpu: ignore[RULE]`
    (or `# ptpu: lockfree[reason]` for by-design lock-free sharing),
    baseline historically-justified ones with --update-baseline plus
    a written justification.
    """
    if files:
        from polyaxon_tpu.polyaxonfile import check_polyaxonfile
        from polyaxon_tpu.polyaxonfile.reader import PolyaxonfileError

        try:
            op = check_polyaxonfile(list(files),
                                    params=_parse_params(params))
        except (PolyaxonfileError, ValueError) as e:
            raise click.ClickException(str(e))
        kind = (getattr(op.component.run, "kind", "?")
                if op.has_component else "ref")
        click.echo(f"Valid operation: name={op.name!r} kind={kind}"
                   + (f" matrix={op.matrix.kind}" if op.matrix else ""))
        return

    if params:
        # -P only means something to polyaxonfile validation: a CI
        # line that lost its -f must fail loudly, not silently run
        # the analyzer and report lint status as file validity.
        raise click.ClickException(
            "-P/--param requires -f (polyaxonfile validation); "
            "the static analyzer takes PATHS only")

    import polyaxon_tpu as _pkg
    from polyaxon_tpu.analysis import (DEFAULT_BASELINE,
                                       apply_baseline, check_paths,
                                       load_baseline, save_baseline)
    from polyaxon_tpu.analysis.checker import iter_py_files

    # Findings and baseline entries are keyed by paths relative to
    # the REPO root (the directory holding the package), never the
    # cwd — `ptpu check` must match the committed baseline from any
    # working directory.
    root = os.path.dirname(
        os.path.dirname(os.path.abspath(_pkg.__file__)))
    target = list(paths) or [os.path.join(root, "polyaxon_tpu")]
    for p in target:
        if not os.path.exists(p):
            raise click.ClickException(f"no such path: {p}")

    if lock_graph_path is not None:
        from polyaxon_tpu.analysis import lockgraph as _lockgraph

        sources = {}
        for p in iter_py_files(target):
            rel = os.path.relpath(os.path.abspath(p), root).replace(
                os.sep, "/")
            if _lockgraph.in_program_scope(rel):
                with open(p, encoding="utf-8") as fh:
                    sources[rel] = fh.read()
        graph = _lockgraph.build_lock_graph(
            _lockgraph.build_model(sources))
        with open(lock_graph_path, "w", encoding="utf-8") as fh:
            json.dump(_lockgraph.canonical_graph(graph), fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        click.echo(f"wrote {len(graph.edges)} lock-order edges to "
                   f"{lock_graph_path}")
        return

    if changed_ref is not None:
        # Incremental mode: the checked file set becomes "changed vs
        # REF (plus untracked)" intersected with the target paths.
        # Everything downstream — per-module rules, the program
        # families over the in-scope subset, baseline, exit status —
        # is exactly a full run on those files.
        import subprocess

        def _git(*args):
            return subprocess.run(["git", *args], cwd=root,
                                  capture_output=True, text=True)

        diff = _git("diff", "--name-only", changed_ref, "--", "*.py")
        if diff.returncode != 0:
            raise click.ClickException(
                f"git diff vs {changed_ref!r} failed: "
                f"{diff.stderr.strip() or diff.stdout.strip()}")
        names = set(diff.stdout.split())
        untracked = _git("ls-files", "--others", "--exclude-standard",
                         "--", "*.py")
        if untracked.returncode == 0:
            names.update(untracked.stdout.split())
        roots_abs = [os.path.abspath(t) for t in target]
        target = []
        for name in sorted(names):
            p = os.path.join(root, name)
            if not (name.endswith(".py") and os.path.isfile(p)):
                continue            # deleted files have no findings
            ap = os.path.abspath(p)
            if any(ap == t or ap.startswith(t + os.sep)
                   for t in roots_abs):
                target.append(p)

    baseline_path = baseline_path or DEFAULT_BASELINE
    findings = check_paths(target, root=root)
    if update_baseline:
        previous = load_baseline(baseline_path)
        # Only the CHECKED paths' debt is rewritten: entries for
        # files outside this run's scope are preserved verbatim, so
        # `ptpu check some/subdir --update-baseline` can never drop
        # other files' entries (and their written justifications).
        checked = {
            os.path.relpath(os.path.abspath(f), root).replace(
                os.sep, "/")
            for f in iter_py_files(target)}
        entries = save_baseline(
            baseline_path, findings, previous=previous,
            preserve=[e for e in previous
                      if e["path"] not in checked])
        click.echo(f"wrote {len(entries)} baseline entries to "
                   f"{baseline_path}")
        return
    entries = load_baseline(baseline_path)
    new, stale = apply_baseline(findings, entries)
    if fmt == "json":
        click.echo(json.dumps({
            "checked_paths": target,
            "findings": [f.to_dict() for f in new],
            "baselined": len(findings) - len(new),
            "new": len(new),
            "stale_baseline_entries": stale,
        }, indent=1))
    else:
        for f in new:   # already stably sorted (path, line, rule)
            click.echo(f.render())
        for e in stale:
            click.echo(f"note: stale baseline entry (code fixed?): "
                       f"{e['rule']} {e['path']} [{e['func']}] — "
                       f"run --update-baseline to drop it", err=True)
        click.echo(f"{len(new)} new finding"
                   f"{'' if len(new) == 1 else 's'} "
                   f"({len(findings) - len(new)} baselined)")
    if new:
        raise SystemExit(1)


@cli.command()
@click.argument("url")
@click.option("--timeout", "timeout_s", default=5.0, type=float,
              help="Per-request HTTP timeout (seconds).")
@click.option("--format", "fmt", type=click.Choice(["text", "json"]),
              default="text", help="Report output format.")
def doctor(url, timeout_s, fmt):
    """Tail-latency forensics for a serving endpoint: fetch the
    anomaly-sentry findings from URL (a router — /fleet/anomalies —
    or a single replica — /anomalies), rank phase regressions, and
    print the exemplar request ids that resolve each one to a full
    per-attempt timeline via GET /fleet/requests/<id>."""
    import urllib.error
    import urllib.request

    base = url.rstrip("/")
    if not base.startswith(("http://", "https://")):
        base = "http://" + base

    def fetch(path):
        try:
            with urllib.request.urlopen(base + path,
                                        timeout=timeout_s) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, None
        except (OSError, ValueError) as e:
            raise click.ClickException(
                f"GET {base}{path} failed: {e}")

    # Router first; a replica answers 404 there, so fall back to its
    # own /anomalies (same findings shape, no source= attribution).
    status, body = fetch("/fleet/anomalies")
    source = "/fleet/anomalies"
    if status == 404 or not isinstance(body, dict):
        status, body = fetch("/anomalies")
        source = "/anomalies"
    if status != 200 or not isinstance(body, dict):
        raise click.ClickException(
            f"GET {base}{source} returned {status} "
            f"(forensics disabled on the target?)")
    if fmt == "json":
        click.echo(json.dumps({"url": base, "source": source,
                               **body}, indent=1))
        return
    findings = body.get("findings", [])
    click.echo(f"doctor {base} ({source})")
    for rid in body.get("fetch_errors", []):
        click.echo(f"  warning: replica {rid} did not answer "
                   f"/anomalies; its findings are absent", err=True)
    share = body.get("phase_share")
    if isinstance(share, dict) and share:
        # Single-replica report: one flat share dict; router report:
        # one dict per source.
        per_source = share if all(isinstance(v, dict)
                                  for v in share.values()) \
            else {"self": share}
        click.echo("phase shares (fraction of request wall time):")
        for src in sorted(per_source):
            shares = per_source[src]
            ranked = sorted(shares.items(),
                            key=lambda kv: -float(kv[1]))
            top = ", ".join(f"{ph}={float(v):.3f}"
                            for ph, v in ranked[:5] if float(v) > 0)
            click.echo(f"  {src:>12}: {top or '(no traffic)'}")
    if not findings:
        click.echo("no anomalies: every phase within its baseline "
                   "band (or the sentry is still building baselines)")
        return
    click.echo(f"{len(findings)} anomalous phase"
               f"{'' if len(findings) == 1 else 's'}, worst first:")
    for f in findings:
        src = f.get("source", "self")
        click.echo(
            f"  [{src}] {f.get('phase')}: share "
            f"{float(f.get('share', 0)):.3f} vs baseline "
            f"{float(f.get('baseline_ewma', 0)):.3f} "
            f"(band hi {float(f.get('band_hi', 0)):.3f}, score "
            f"{float(f.get('score', 0)):.3f}, window "
            f"{f.get('window')})")
        for rid in f.get("exemplars", []):
            click.echo(f"      exemplar {rid} -> GET "
                       f"{base}/fleet/requests/{rid}")
        if f.get("bundle"):
            click.echo(f"      bundle {f['bundle']}")
    raise SystemExit(1)


@cli.group()
def config():
    """Show/set client configuration."""


@config.command(name="show")
def config_show():
    import dataclasses

    from polyaxon_tpu.client.store import default_home
    from polyaxon_tpu.config import ClientConfig

    cfg = ClientConfig.load()
    click.echo(f"home: {default_home()}")
    for key, value in dataclasses.asdict(cfg).items():
        if key == "token" and value:
            value = "****"  # never echo secrets
        click.echo(f"{key}: {value}")


@config.command(name="set")
@click.argument("pairs", nargs=-1, required=True)
def config_set(pairs):
    """Persist config values: ptpu config set host=http://cp:8000."""
    from polyaxon_tpu.config import ClientConfig

    parsed = {}
    for pair in pairs:
        if "=" not in pair:
            raise click.ClickException(f"expected key=value, got {pair!r}")
        key, _, value = pair.partition("=")
        parsed[key.strip()] = value
    try:
        path = ClientConfig.set_file_values(parsed)
    except KeyError as e:
        raise click.ClickException(str(e))
    click.echo(f"saved {path}")


@config.command(name="get")
@click.argument("key")
def config_get(key):
    import dataclasses

    from polyaxon_tpu.config import ClientConfig

    cfg = dataclasses.asdict(ClientConfig.load())
    if key not in cfg:
        raise click.ClickException(
            f"unknown key {key!r}; known: {sorted(cfg)}")
    click.echo(cfg[key])


@cli.command()
def version():
    """Print versions (framework + runtime stack)."""
    click.echo(f"polyaxon-tpu {__version__}")
    try:
        import jax

        click.echo(f"jax {jax.__version__}")
    except ImportError:
        pass


@cli.command(name="port-forward")
@click.argument("run_uuid")
@click.option("--port", "-p", default=None, type=int,
              help="Local port (default: same as the service port).")
@click.option("--target", default=None,
              help="Override target host:port (default: the run's "
                   "recorded endpoint, else 127.0.0.1:<service port>).")
def port_forward(run_uuid, port, target):
    """Forward a local port to a service run (notebook/TensorBoard)."""
    import socket
    import socketserver
    import threading

    record = _get_run_or_fail(run_uuid)
    meta = record.get("meta_info") or {}
    if target is None:
        target = meta.get("endpoint")
    if target is None:
        # A locally-executed service records its live ports
        # (runner.local._run_service).
        svc = meta.get("service") or {}
        if svc.get("ports"):
            target = (f"{svc.get('host', '127.0.0.1')}:"
                      f"{svc['ports'][0]}")
    if target is None:
        content = record.get("content") or {}
        run_section = (content.get("component") or {}).get("run") or {}
        ports = run_section.get("ports") or []
        if not ports:
            raise click.ClickException(
                f"Run {run_uuid} declares no service ports; pass --target")
        target = f"127.0.0.1:{ports[0]}"
    host, _, tport = target.partition(":")
    tport = int(tport or 80)
    local_port = port or tport

    class Relay(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                upstream = socket.create_connection((host, tport),
                                                    timeout=10)
            except OSError as e:
                self.request.close()
                click.echo(f"connect {host}:{tport} failed: {e}", err=True)
                return

            def pump(src, dst):
                try:
                    while True:
                        data = src.recv(65536)
                        if not data:
                            break
                        dst.sendall(data)
                except OSError:
                    pass
                finally:
                    # Half-close only: EOF on src ends THIS direction;
                    # the reverse pump keeps relaying the response.
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass

            t = threading.Thread(target=pump,
                                 args=(upstream, self.request),
                                 daemon=True)
            t.start()
            pump(self.request, upstream)
            t.join(timeout=5)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("127.0.0.1", local_port), Relay) as server:
        click.echo(f"forwarding 127.0.0.1:{local_port} -> {host}:{tport} "
                   "(ctrl-c to stop)")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass


# ---------------------------------------------------------------------------
# project
# ---------------------------------------------------------------------------


@cli.group()
def project():
    """Inspect projects (namespaces grouping runs)."""


@project.command(name="ls")
def project_ls():
    from collections import Counter

    counts = Counter(r.get("project") or "default"
                     for r in _store().list_runs())
    for name, n in sorted(counts.items()):
        click.echo(f"{name:<24} {n} runs")


@project.command(name="runs")
@click.argument("name")
@click.option("--limit", default=20, type=int)
def project_runs(name, limit):
    for r in _store().list_runs(project=name, limit=limit):
        click.echo(f"{r['uuid']}  {r.get('status', ''):<10} "
                   f"{r.get('name', '')}")


# ---------------------------------------------------------------------------
# auth
# ---------------------------------------------------------------------------


@cli.group()
def auth():
    """Authentication against the control plane."""


@auth.command(name="login")
@click.option("--token", prompt=True, hide_input=True,
              help="API token (prompted when omitted).")
@click.option("--host", default=None)
def auth_login(token, host):
    from polyaxon_tpu.config import ClientConfig

    values = {"token": token}
    if host:
        values["host"] = host
    ClientConfig.set_file_values(values)
    click.echo("logged in (token stored in home config)")


@auth.command(name="logout")
def auth_logout():
    from polyaxon_tpu.config import ClientConfig

    ClientConfig.unset_file_values(["token"])
    click.echo("logged out")


@auth.command(name="whoami")
def auth_whoami():
    from polyaxon_tpu.config import ClientConfig

    cfg = ClientConfig.load()
    click.echo(f"host: {cfg.host or '(local mode)'}")
    click.echo(f"token: {'set' if cfg.token else '(none)'}")


# ---------------------------------------------------------------------------
# admin
# ---------------------------------------------------------------------------


@cli.group()
def admin():
    """Deployment management."""


@admin.command(name="deploy")
@click.option("--namespace", default="polyaxon-tpu")
@click.option("--image", default="polyaxon-tpu/core:latest")
@click.option("--operator-image", default="polyaxon-tpu/operator:latest")
@click.option("--artifacts-claim", default=None)
@click.option("-o", "--output", default="-",
              help="Write manifests to a file ('-' = stdout).")
def admin_deploy(namespace, image, operator_image, artifacts_claim, output):
    """Render the k8s manifests for a full deployment (CRD, RBAC,
    control plane, agent, native operator)."""
    import yaml as _yaml

    from polyaxon_tpu.deploy import DeploymentConfig, render_all

    manifests = render_all(DeploymentConfig(
        namespace=namespace, image=image, operator_image=operator_image,
        artifacts_claim=artifacts_claim))
    text = "---\n".join(_yaml.safe_dump(m, sort_keys=False)
                        for m in manifests)
    if output == "-":
        click.echo(text)
    else:
        with open(output, "w") as f:
            f.write(text)
        click.echo(f"wrote {len(manifests)} manifests to {output}")


# ---------------------------------------------------------------------------
# control plane + agent services
# ---------------------------------------------------------------------------


@cli.command()
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8000, type=int)
@click.option("--schedules/--no-schedules", default=True,
              help="Also run the schedule-materializer loop.")
@click.option("--auth-token", default=None, envvar="POLYAXON_TPU_AUTH_TOKEN",
              help="Require this bearer token on every request.")
def server(host, port, schedules, auth_token):
    """Serve the control plane API (runs DB, queue, streams,
    dashboard at /ui, Prometheus gauges at /metrics)."""
    import threading

    from polyaxon_tpu.client.store import FileRunStore
    from polyaxon_tpu.scheduler import ControlPlane, ScheduleService, \
        make_server

    store = FileRunStore()
    srv = make_server(host, port, store,
                      plane=ControlPlane(store, auth_token=auth_token))
    if schedules:
        service = ScheduleService(store)
        threading.Thread(target=service.run_forever, daemon=True).start()
    click.echo(f"control plane on http://{host}:{port} (home={store.home})")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()


@cli.command()
@click.option("--name", default="agent-0")
@click.option("--host", default=None,
              help="Control plane URL (default: POLYAXON_TPU_HOST, else "
                   "in-process over the local store).")
@click.option("--backend", type=click.Choice(["local", "manifest", "kube"]),
              default="local")
@click.option("--cluster-dir", default=None,
              help="Manifest backend: directory the operator watches.")
@click.option("--max-concurrent", default=8, type=int)
@click.option("--queue", "queues", multiple=True,
              help="Serve only these queues (repeatable; default: all).")
def agent(name, host, backend, cluster_dir, max_concurrent, queues):
    """Run an agent: claim queued runs and execute them."""
    from polyaxon_tpu.runner.agent import (Agent, KubeBackend, LocalBackend,
                                           ManifestBackend)
    from polyaxon_tpu.scheduler import ControlPlane

    host = host or os.environ.get("POLYAXON_TPU_HOST")
    if host:
        from polyaxon_tpu.client.api_client import ApiRunStore

        plane = ApiRunStore(host)
    else:
        plane = ControlPlane()

    if backend == "manifest":
        if not cluster_dir:
            raise click.ClickException(
                "--backend manifest requires --cluster-dir")
        be = ManifestBackend(cluster_dir)
    elif backend == "kube":
        # API server + token from PTPU_K8S_* env or in-cluster config.
        be = KubeBackend()
    else:
        store = getattr(plane, "store", plane)
        be = LocalBackend(store)
    worker = Agent(plane, backend=be, name=name,
                   max_concurrent=max_concurrent,
                   queues=list(queues) or None)
    click.echo(f"agent {name} polling "
               f"{host or 'local store'} (backend={backend})")
    try:
        worker.run_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    cli()
