"""CLI entrypoint. Command groups are registered as subsystems land."""

from __future__ import annotations

import click

from polyaxon_tpu import __version__


@click.group(name="ptpu")
@click.version_option(version=__version__, prog_name="polyaxon-tpu")
def cli():
    """polyaxon-tpu: TPU-native ML orchestration."""


if __name__ == "__main__":
    cli()
