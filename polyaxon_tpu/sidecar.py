"""Sidecar entrypoint: ``python -m polyaxon_tpu.sidecar``.

The watcher-uploader auxiliary (SURVEY.md 2.10/5.5, plane (a)/(b)): tails
the run's local outputs/events directories and syncs them to the
artifacts store mount at an interval, with a final sync on shutdown.
In-cluster the store mount is a connection volume; locally the runner
points it at the run store's artifacts root.
"""

from __future__ import annotations

import argparse
import os
import shutil
import signal
import sys
import time
from typing import Optional


def _sync_tree(src: str, dst: str) -> int:
    """Copy changed files src -> dst; returns files copied."""
    if not os.path.isdir(src):
        return 0
    copied = 0
    for root, _, files in os.walk(src):
        rel = os.path.relpath(root, src)
        target_dir = os.path.join(dst, rel) if rel != "." else dst
        os.makedirs(target_dir, exist_ok=True)
        for name in files:
            s = os.path.join(root, name)
            d = os.path.join(target_dir, name)
            try:
                if (not os.path.exists(d)
                        or os.path.getmtime(s) > os.path.getmtime(d)
                        or os.path.getsize(s) != os.path.getsize(d)):
                    shutil.copy2(s, d)
                    copied += 1
            except OSError:
                continue  # file mid-write; next tick gets it
    return copied


class Sidecar:
    def __init__(self, run_uuid: str, local_root: str, store_root: str,
                 sync_interval: int = 10, collect_logs: bool = True,
                 collect_artifacts: bool = True):
        self.run_uuid = run_uuid
        self.local_root = local_root
        self.store_root = store_root
        self.sync_interval = max(1, sync_interval)
        self.collect_logs = collect_logs
        self.collect_artifacts = collect_artifacts
        self._stop = False

    def sync_once(self) -> int:
        copied = 0
        dst = os.path.join(self.store_root, self.run_uuid)
        if self.collect_artifacts:
            # Store layout (client.store): events/, artifacts/ (outputs
            # inside); plus bare outputs/assets for unmanaged local dirs.
            for sub in ("artifacts", "events", "outputs", "assets"):
                copied += _sync_tree(os.path.join(self.local_root, sub),
                                     os.path.join(dst, sub))
        if self.collect_logs:
            copied += _sync_tree(os.path.join(self.local_root, "logs"),
                                 os.path.join(dst, "logs"))
        return copied

    def run(self, max_ticks: Optional[int] = None) -> None:
        def stop(signum, frame):
            self._stop = True

        signal.signal(signal.SIGTERM, stop)
        signal.signal(signal.SIGINT, stop)
        ticks = 0
        while not self._stop:
            self.sync_once()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
            deadline = time.time() + self.sync_interval
            while time.time() < deadline and not self._stop:
                time.sleep(0.2)
        self.sync_once()  # final sync


def main(argv=None) -> int:
    from .k8s.auxiliaries import ARTIFACTS_MOUNT

    parser = argparse.ArgumentParser(prog="polyaxon_tpu.sidecar")
    parser.add_argument("--run-uuid", required=True)
    parser.add_argument("--local-root", default=None,
                        help="run's local working dir (default: cwd/.ptpu)")
    parser.add_argument("--store-root", default=None)
    parser.add_argument("--sync-interval", type=int, default=10)
    parser.add_argument("--collect-logs", default="true")
    parser.add_argument("--collect-artifacts", default="true")
    parser.add_argument("--max-ticks", type=int, default=None)
    args = parser.parse_args(argv)

    local_root = args.local_root or os.path.join(os.getcwd(), ".ptpu",
                                                 args.run_uuid)
    store_root = args.store_root or os.environ.get(
        "POLYAXON_TPU_ARTIFACTS_PATH", ARTIFACTS_MOUNT)
    # The env var points at the run's dir; the sidecar writes runs under
    # the store root, so strip a trailing run-uuid path segment.
    if os.path.basename(store_root.rstrip("/")) == args.run_uuid:
        store_root = os.path.dirname(store_root.rstrip("/"))

    Sidecar(
        run_uuid=args.run_uuid,
        local_root=local_root,
        store_root=store_root,
        sync_interval=args.sync_interval,
        collect_logs=args.collect_logs != "false",
        collect_artifacts=args.collect_artifacts != "false",
    ).run(max_ticks=args.max_ticks)
    return 0


if __name__ == "__main__":
    sys.exit(main())
