"""Query/filter/sort DSL for run records.

Parity with the reference's query language (SURVEY.md 2.16) used by
``ops ls --query`` and tuner joins:

    status:running
    status:running|queued            (OR within a field)
    metrics.loss:<0.1
    tags:tpu, project:vision        (comma = AND)
    name:~resnet                    (~ prefix = negate; bare substring match)
    created_at:>2026-01-01
    uuid:abc123..def456             (range)

Sort: comma-separated field names, ``-`` prefix for descending:
``--sort="-created_at,name"``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class QueryError(ValueError):
    pass


def _coerce(value: str) -> Any:
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    return value


def _get_field(record: Dict[str, Any], field: str,
               metrics_reader: Optional[Callable] = None) -> Any:
    if field.startswith("metrics."):
        name = field[len("metrics."):]
        metrics = record.get("_metrics")
        if metrics is None and metrics_reader is not None:
            metrics = metrics_reader(record["uuid"])
            record["_metrics"] = metrics
        return (metrics or {}).get(name)
    if field.startswith(("inputs.", "outputs.", "meta_info.")):
        ns, _, key = field.partition(".")
        return (record.get(ns) or {}).get(key)
    return record.get(field)


def _match_one(actual: Any, cond: str) -> bool:
    negate = False
    if cond.startswith("~"):
        negate, cond = True, cond[1:]
    result = _compare(actual, cond)
    return (not result) if negate else result


def _ordered(op, actual: Any, expected: Any) -> bool:
    """Ordered comparison that never raises on mixed types.

    ISO dates in the query (created_at:>2026-01-01) are converted to epoch
    floats so they compare correctly against the store's float timestamps;
    any remaining type mismatch falls back to string comparison.
    """
    if actual is None:
        return False
    if isinstance(actual, (int, float)) and isinstance(expected, str):
        try:
            from datetime import datetime, timezone

            dt = datetime.fromisoformat(expected)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=timezone.utc)
            expected = dt.timestamp()
        except ValueError:
            pass
    try:
        return op(actual, expected)
    except TypeError:
        return op(str(actual), str(expected))


def _compare(actual: Any, cond: str) -> bool:
    import operator

    if cond.startswith(">="):
        return _ordered(operator.ge, actual, _coerce(cond[2:]))
    if cond.startswith("<="):
        return _ordered(operator.le, actual, _coerce(cond[2:]))
    if cond.startswith(">"):
        return _ordered(operator.gt, actual, _coerce(cond[1:]))
    if cond.startswith("<"):
        return _ordered(operator.lt, actual, _coerce(cond[1:]))
    if ".." in cond:
        lo, _, hi = cond.partition("..")
        return (_ordered(operator.ge, actual, _coerce(lo))
                and _ordered(operator.le, actual, _coerce(hi)))
    if isinstance(actual, list):
        return _coerce(cond) in actual or cond in actual
    if isinstance(actual, str):
        return actual == cond or (len(cond) > 0 and cond in actual
                                  and not cond.replace(".", "").isdigit())
    return actual == _coerce(cond)


def parse_query(query: str) -> List[tuple]:
    """-> [(field, [or_conditions...]), ...] (AND over the list)."""
    clauses = []
    for part in query.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise QueryError(
                f"Bad query clause {part!r}: expected field:condition"
            )
        field, _, cond = part.partition(":")
        ors = [c.strip() for c in cond.split("|") if c.strip()]
        if not ors:
            raise QueryError(f"Bad query clause {part!r}: empty condition")
        clauses.append((field.strip(), ors))
    return clauses


def apply_query(records: List[Dict[str, Any]], query: str,
                metrics_reader: Optional[Callable] = None) -> List[Dict[str, Any]]:
    clauses = parse_query(query)

    def keep(record: Dict[str, Any]) -> bool:
        for field, ors in clauses:
            actual = _get_field(record, field, metrics_reader)
            if not any(_match_one(actual, c) for c in ors):
                return False
        return True

    return [r for r in records if keep(r)]


def apply_sort(records: List[Dict[str, Any]], sort: str) -> List[Dict[str, Any]]:
    for field in reversed([s.strip() for s in sort.split(",") if s.strip()]):
        reverse = field.startswith("-")
        if reverse:
            field = field[1:]

        def key(r, f=field):
            v = _get_field(r, f)
            return (v is None, v)

        try:
            records = sorted(records, key=key, reverse=reverse)
        except TypeError:  # mixed types in the field: fall back to str
            records = sorted(
                records,
                key=lambda r, f=field: str(_get_field(r, f)),
                reverse=reverse,
            )
    return records
