"""Typed connections: external stores/services a run mounts or reaches.

Parity: reference connection schemas + fs adapters (SURVEY.md 2.13;
expected at ``polyaxon/_connections/`` — unverified).  A connection has
a kind (object store / volume / git / registry), a typed config schema,
and optional secret/config-map references the converter materializes as
env or mounts.  Filesystem access goes through ``fs_adapter``: local
paths natively, fsspec-backed schemes (gs://, s3://) when the optional
dependency is present — gated, never imported at module load.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from pydantic import field_validator

from .flow.base import BaseSchema


class ConnectionKind:
    HOST_PATH = "host_path"
    VOLUME_CLAIM = "volume_claim"
    GCS = "gcs"
    S3 = "s3"
    WASB = "wasb"  # azure blob
    GIT = "git"
    REGISTRY = "registry"
    SLACK = "slack"
    WEBHOOK = "webhook"

    MOUNTABLE = {HOST_PATH, VOLUME_CLAIM}
    BLOB = {GCS, S3, WASB}
    ARTIFACT = MOUNTABLE | BLOB


class V1HostPathConnection(BaseSchema):
    host_path: str
    mount_path: Optional[str] = None
    read_only: Optional[bool] = None


class V1ClaimConnection(BaseSchema):
    volume_claim: str
    mount_path: str
    read_only: Optional[bool] = None


class V1BucketConnection(BaseSchema):
    bucket: str


class V1GitConnection(BaseSchema):
    url: str
    revision: Optional[str] = None
    flags: Optional[List[str]] = None


class V1UrlConnection(BaseSchema):
    url: str


class V1ConnectionResource(BaseSchema):
    """A k8s secret or config-map the connection needs at runtime."""

    name: str
    mount_path: Optional[str] = None
    items: Optional[List[str]] = None
    default_mode: Optional[str] = None
    is_requested: Optional[bool] = None


_SCHEMA_BY_KIND = {
    ConnectionKind.HOST_PATH: V1HostPathConnection,
    ConnectionKind.VOLUME_CLAIM: V1ClaimConnection,
    ConnectionKind.GCS: V1BucketConnection,
    ConnectionKind.S3: V1BucketConnection,
    ConnectionKind.WASB: V1BucketConnection,
    ConnectionKind.GIT: V1GitConnection,
    ConnectionKind.REGISTRY: V1UrlConnection,
    ConnectionKind.SLACK: V1UrlConnection,
    ConnectionKind.WEBHOOK: V1UrlConnection,
}


class V1Connection(BaseSchema):
    """A named, typed external resource."""

    name: str
    kind: str
    description: Optional[str] = None
    tags: Optional[List[str]] = None
    schema_: Optional[Dict[str, Any]] = None
    secret: Optional[V1ConnectionResource] = None
    config_map: Optional[V1ConnectionResource] = None
    env: Optional[List[Dict[str, Any]]] = None
    annotations: Optional[Dict[str, str]] = None

    @field_validator("kind")
    @classmethod
    def _kind(cls, v):
        if v not in _SCHEMA_BY_KIND:
            raise ValueError(
                f"Unknown connection kind {v!r}; known: "
                f"{sorted(_SCHEMA_BY_KIND)}")
        return v

    def typed_schema(self):
        """Validate + return the kind-specific config."""
        cls = _SCHEMA_BY_KIND[self.kind]
        return cls.from_dict(self.schema_ or {})

    @property
    def is_artifact_store(self) -> bool:
        return self.kind in ConnectionKind.ARTIFACT

    def store_root(self) -> str:
        """Filesystem-ish root for artifact-store kinds."""
        schema = self.typed_schema()
        if self.kind == ConnectionKind.HOST_PATH:
            return schema.host_path
        if self.kind == ConnectionKind.VOLUME_CLAIM:
            return schema.mount_path
        if self.kind in ConnectionKind.BLOB:
            prefix = {"gcs": "gs://", "s3": "s3://",
                      "wasb": "wasb://"}[self.kind]
            bucket = schema.bucket
            return bucket if "://" in bucket else prefix + bucket
        raise ValueError(
            f"Connection {self.name!r} ({self.kind}) is not an artifact "
            "store")

    def env_name(self) -> str:
        """Env var the initializer resolves this connection's root from."""
        return ("POLYAXON_TPU_CONNECTION_"
                + self.name.upper().replace("-", "_") + "_ROOT")


class ConnectionCatalog:
    """The deployment's named connections (agent/converter side).

    Loaded from a JSON/YAML catalog file (``POLYAXON_TPU_CONNECTIONS_FILE``)
    or built programmatically.  The converter asks it for volumes/env to
    attach; the initializer resolves roots via the env vars it emits.
    """

    def __init__(self, connections: Optional[List[V1Connection]] = None):
        self._by_name: Dict[str, V1Connection] = {
            c.name: c for c in connections or []}

    @classmethod
    def load(cls, path: Optional[str] = None) -> "ConnectionCatalog":
        path = path or os.environ.get("POLYAXON_TPU_CONNECTIONS_FILE")
        if not path:
            from .config import ClientConfig

            path = ClientConfig.read_file_layer().get("connections_file")
        if not path or not os.path.exists(path):
            return cls()
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or []
        if isinstance(data, dict):
            data = data.get("connections") or []
        return cls([V1Connection.from_dict(d) for d in data])

    def get(self, name: str) -> V1Connection:
        if name not in self._by_name:
            raise KeyError(
                f"Unknown connection {name!r}; cataloged: "
                f"{sorted(self._by_name)}")
        return self._by_name[name]

    def names(self) -> List[str]:
        return sorted(self._by_name)

    def add(self, connection: V1Connection) -> None:
        self._by_name[connection.name] = connection

    # -- converter hooks -------------------------------------------------

    def volume_for(self, name: str) -> Optional[Dict[str, Any]]:
        """k8s volume spec for mountable kinds (None for blob/url kinds)."""
        conn = self.get(name)
        schema = conn.typed_schema()
        if conn.kind == ConnectionKind.HOST_PATH:
            return {"name": f"conn-{name}",
                    "hostPath": {"path": schema.host_path}}
        if conn.kind == ConnectionKind.VOLUME_CLAIM:
            return {"name": f"conn-{name}",
                    "persistentVolumeClaim":
                        {"claimName": schema.volume_claim}}
        return None

    def mount_for(self, name: str) -> Optional[Dict[str, Any]]:
        conn = self.get(name)
        schema = conn.typed_schema()
        if conn.kind == ConnectionKind.HOST_PATH:
            return {"name": f"conn-{name}",
                    "mountPath": schema.mount_path or schema.host_path,
                    "readOnly": bool(schema.read_only)}
        if conn.kind == ConnectionKind.VOLUME_CLAIM:
            return {"name": f"conn-{name}",
                    "mountPath": schema.mount_path,
                    "readOnly": bool(schema.read_only)}
        return None

    def env_for(self, name: str) -> List[Dict[str, Any]]:
        """Env entries advertising the connection root + custom env."""
        conn = self.get(name)
        env: List[Dict[str, Any]] = []
        if conn.is_artifact_store:
            mount = self.mount_for(name)
            root = (mount["mountPath"] if mount else conn.store_root())
            env.append({"name": conn.env_name(), "value": root})
        env.extend(conn.env or [])
        if conn.secret and not conn.secret.mount_path:
            # env-style secret: expose every requested key
            for key in conn.secret.items or []:
                env.append({
                    "name": key,
                    "valueFrom": {"secretKeyRef":
                                  {"name": conn.secret.name, "key": key}},
                })
        return env

    def resource_volumes_for(self, name: str):
        """(volumes, mounts) for mounted secrets/config-maps — e.g. a GCS
        service-account keyfile at its mount_path."""
        conn = self.get(name)
        volumes: List[Dict[str, Any]] = []
        mounts: List[Dict[str, Any]] = []
        if conn.secret and conn.secret.mount_path:
            vol_name = f"secret-{conn.secret.name}"
            volumes.append({"name": vol_name,
                            "secret": {"secretName": conn.secret.name}})
            mounts.append({"name": vol_name,
                           "mountPath": conn.secret.mount_path,
                           "readOnly": True})
        if conn.config_map and conn.config_map.mount_path:
            vol_name = f"cm-{conn.config_map.name}"
            volumes.append({"name": vol_name,
                            "configMap": {"name": conn.config_map.name}})
            mounts.append({"name": vol_name,
                           "mountPath": conn.config_map.mount_path,
                           "readOnly": True})
        return volumes, mounts


# -- filesystem adapter ----------------------------------------------------


def fs_adapter(root: str):
    """Filesystem for a store root: local paths natively, remote schemes
    through fsspec when available (gated — zero hard deps)."""
    if "://" not in root:
        return _LocalFs(root)
    try:
        import fsspec  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            f"Remote store root {root!r} needs fsspec, which is not "
            "installed in this environment; use a mounted/local "
            "connection instead") from e
    fs, path = fsspec.core.url_to_fs(root)
    return _FsspecFs(fs, path)


class _LocalFs:
    def __init__(self, root: str):
        self.root = root

    def _p(self, rel: str) -> str:
        return os.path.join(self.root, rel) if rel else self.root

    def open(self, rel: str, mode: str = "r"):
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(self._p(rel)), exist_ok=True)
        return open(self._p(rel), mode)

    def exists(self, rel: str) -> bool:
        return os.path.exists(self._p(rel))

    def listdir(self, rel: str = "") -> List[str]:
        path = self._p(rel)
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def makedirs(self, rel: str) -> None:
        os.makedirs(self._p(rel), exist_ok=True)

    def upload(self, local_path: str, rel: str) -> None:
        import shutil

        dest = self._p(rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, dest, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, dest)

    def download(self, rel: str, local_path: str) -> None:
        import shutil

        src = self._p(rel)
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, local_path, dirs_exist_ok=True)
        else:
            shutil.copy2(src, local_path)


class _FsspecFs:
    def __init__(self, fs, root: str):
        self.fs = fs
        self.root = root

    def _p(self, rel: str) -> str:
        return f"{self.root}/{rel}" if rel else self.root

    def open(self, rel: str, mode: str = "r"):
        return self.fs.open(self._p(rel), mode)

    def exists(self, rel: str) -> bool:
        return self.fs.exists(self._p(rel))

    def listdir(self, rel: str = "") -> List[str]:
        return sorted(os.path.basename(p)
                      for p in self.fs.ls(self._p(rel)))

    def makedirs(self, rel: str) -> None:
        self.fs.makedirs(self._p(rel), exist_ok=True)

    def upload(self, local_path: str, rel: str) -> None:
        self.fs.put(local_path, self._p(rel), recursive=True)

    def download(self, rel: str, local_path: str) -> None:
        self.fs.get(self._p(rel), local_path, recursive=True)
