"""Generic training driver: ``python -m polyaxon_tpu.train --model NAME``.

This is the in-container entrypoint the five BASELINE configs run — the
piece that ties the runtime together exactly as the north-star demands
(SURVEY.md 3.2/5.8):

    1. ``parallel.bootstrap.initialize_from_env()``  — multi-host
       jax.distributed bootstrap from the agent/operator-injected
       ``PTPU_*`` env (replaces TF_CONFIG/NCCL/MPI);
    2. mesh from ``--strategy`` (or ``PTPU_STRATEGY`` env) over all
       connected devices — DP/FSDP/TP axes via the strategy library;
    3. ``tracking.init()``  — run identity from injected env; stepped
       metrics (loss, accuracy, throughput img-or-tok/sec/chip);
    4. Orbax checkpointing with auto-resume + SIGTERM preemption save.

Data is synthetic by default (deterministic; benchmarks measure compute,
not input pipelines); a ``--data-dir`` of .npy files plugs real arrays
into the same path.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time
from typing import Any, Dict, Optional


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="polyaxon_tpu.train")
    p.add_argument("--model", default="mlp")
    p.add_argument("--steps", type=int, default=None,
                   help="Total optimizer steps (overrides epochs).")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="Default: the dataset's epoch length; synthetic "
                        "data keeps the historical 100-step epoch.")
    p.add_argument("--batch-size", type=int, default=None,
                   help="GLOBAL batch size (sharded over dp/fsdp).")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--optimizer", default="adamw",
                   choices=["adamw", "sgd", "adam"])
    p.add_argument("--strategy", default=None,
                   help='Mesh axes: JSON (\'{"dp": -1, "tp": 2}\') or '
                        'compact "dp:2,tp:2" / "dp=2,tp=2" '
                        "(default: PTPU_STRATEGY env, else pure DP).")
    p.add_argument("--sp-mode", default="ring",
                   choices=["ring", "ulysses"],
                   help="Sequence-parallel attention flavor when the "
                        "strategy has sp > 1.")
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="Steps between checkpoints (0 = only at end).")
    p.add_argument("--resume", action="store_true", default=True)
    p.add_argument("--no-resume", dest="resume", action="store_false")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--profile-at", type=int, default=0,
                   help="Capture a jax.profiler trace starting at this "
                        "step (0 = off).")
    p.add_argument("--profile-steps", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--init-hf", default=None, metavar="STATE_DICT",
                   help="Initialize params from a torch state_dict "
                        "file (HF checkpoint) instead of random init "
                        "— the fine-tuning path.  The mapping is the "
                        "verified models/import_hf loader for the "
                        "model family; dims must match --model.")
    p.add_argument("--data-dir", default=None,
                   help="Directory of inputs.npy/labels.npy (else "
                        "synthetic).")
    p.add_argument("--dataset", default=None,
                   choices=["synthetic", "digits", "npy", "tokens",
                            "span-corruption"],
                   help="Input source (default: npy when --data-dir is "
                        "given, else synthetic).  'digits' is the real "
                        "offline 10-class image set (BASELINE config 1); "
                        "'tokens' samples LM windows from tokens.npy/"
                        "tokens.bin under --data-dir.")
    p.add_argument("--seq-len", type=int, default=None,
                   help="Window length for --dataset tokens (default: "
                        "the model's synthetic batch seq length).")
    p.add_argument("--eval-every", type=int, default=0,
                   help="Steps between held-out evals (0 = end only; "
                        "needs a dataset with an eval split).")
    p.add_argument("--prefetch", type=int, default=2,
                   help="Device-prefetch depth (0 disables).")
    p.add_argument("--cpu", action="store_true",
                   help="Force the CPU backend.")
    p.add_argument("--target-metric", default=None,
                   help="name>=value or name<=value (plain name=value "
                        "infers direction: loss/error/perplexity-like "
                        "names minimize, everything else maximizes); "
                        "exit once the metric reaches value.")
    return p


_MINIMIZE_HINTS = ("loss", "error", "err", "perplexity", "ppl", "nll",
                   "mse", "mae", "rmse")


def parse_target_metric(spec):
    """``name>=value`` / ``name<=value`` / ``name=value`` -> (name, value,
    op).  A plain ``=`` infers direction from the metric name: a
    minimizing target like ``loss=0.1`` must NOT be satisfied by the
    (large) initial loss (ADVICE r1)."""
    if not spec or "=" not in spec:
        return None
    if ">=" in spec:
        name, _, val = spec.partition(">=")
        op = ">="
    elif "<=" in spec:
        name, _, val = spec.partition("<=")
        op = "<="
    else:
        name, _, val = spec.partition("=")
        lowered = name.strip().lower()
        op = "<=" if any(h in lowered for h in _MINIMIZE_HINTS) else ">="
    return (name.strip(), float(val), op)


def target_reached(value, target) -> bool:
    _, threshold, op = target
    return value <= threshold if op == "<=" else value >= threshold


def load_hf_init(model_name: str, model, path: str):
    """Fine-tuning init: map a torch ``state_dict`` file onto the zoo
    model's params via the verified ``models.import_hf`` loader for
    the family (numerics pinned vs transformers in
    tests/test_import_hf.py).  The checkpoint's dims must match the
    zoo config — a mismatch surfaces as a loader shape error naming
    the offending tensor, not silent garbage."""
    import torch

    from .models import import_hf

    family = model_name.split("-")[0]
    loader_name = _HF_LOADER_BY_FAMILY.get(family)
    if loader_name is None:
        raise SystemExit(
            f"--init-hf supports the {sorted(_HF_LOADER_BY_FAMILY)} "
            f"families, not {model_name!r}")
    state_dict = torch.load(path, map_location="cpu",
                            weights_only=True)
    return getattr(import_hf, loader_name)(state_dict, model.cfg)


_HF_LOADER_BY_FAMILY = {
    "bert": "load_hf_bert",
    "gpt2": "load_hf_gpt2",
    "llama": "load_hf_llama",
    "tinyllama": "load_hf_llama",
    "mistral": "load_hf_llama",  # same block layout
    "vit": "load_hf_vit",
    "t5": "load_hf_t5",
}

# Config overrides a family needs for HF-parity fine-tuning, applied
# to make_model when --init-hf is set (kept next to the loader table
# so a new family states both halves of its contract in one place).
# bert/vit: HF uses the exact (erf) GELU; the zoo default is tanh.
_HF_MODEL_KW = {
    "bert": {"gelu_approximate": False},
    "vit": {"gelu_approximate": False},
}


def make_optimizer(name: str, lr: float):
    import optax

    if name == "sgd":
        return optax.sgd(lr, momentum=0.9)
    if name == "adam":
        return optax.adam(lr)
    return optax.adamw(lr, weight_decay=0.01)


def make_datasets(args, spec, batch_size: int, model=None):
    """(train ArrayDataset, eval ArrayDataset or None).  ``model``:
    the already-constructed model (span-corruption reads its config
    and seq2seq-ness instead of building a throwaway copy)."""
    from . import data

    kind = args.dataset or ("npy" if args.data_dir else "synthetic")
    if kind == "npy":
        if not args.data_dir:
            raise SystemExit("--dataset npy requires --data-dir")
        return data.npy_dataset(args.data_dir, batch_size,
                                seed=args.seed), None
    if kind == "tokens":
        if not args.data_dir:
            raise SystemExit("--dataset tokens requires --data-dir")
        seq_len = args.seq_len or \
            spec.make_batch(1)["inputs"].shape[-1]
        return data.token_dataset(args.data_dir, batch_size, seq_len,
                                  seed=args.seed), None
    if kind == "span-corruption":
        # T5-style denoising pretraining over a token stream
        # (data.SpanCorruptionDataset).
        if not args.data_dir:
            raise SystemExit("--dataset span-corruption requires "
                             "--data-dir")
        model = model if model is not None else spec.make_model()
        if not hasattr(model, "encode"):
            raise SystemExit(
                f"--dataset span-corruption requires a seq2seq "
                f"(encoder-decoder) model; {args.model!r} is not "
                f"(use a t5-* model)")
        cfg = model.cfg
        seq_len = args.seq_len or \
            spec.make_batch(1)["inputs"].shape[-1]
        stream = data.token_dataset(args.data_dir, batch_size, seq_len,
                                    seed=args.seed)
        return data.SpanCorruptionDataset(
            stream.tokens, batch_size, inputs_length=seq_len,
            targets_length=max(32, seq_len // 4),
            vocab_size=cfg.vocab_size, pad_id=cfg.pad_id,
            seed=args.seed), None
    if kind == "digits":
        train = data.digits_dataset(batch_size, split="train",
                                    seed=args.seed)
        evals = data.digits_dataset(batch_size, split="eval",
                                    seed=args.seed)
        return train, evals
    return data.synthetic_dataset(spec, batch_size, seed=args.seed), None


def make_eval_fn(model, mesh, batch_sharding):
    """Jitted held-out accuracy over an ArrayDataset."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    # The final partial batch of an eval split is rarely divisible by
    # the data-sharded mesh axes; pad it up and mask the padding out of
    # the correct-count (a real 103-sample digits split on an 8-way
    # mesh must not crash the run).
    from .parallel.mesh import active_batch_axes

    divisor = 1
    for name in active_batch_axes(mesh, ("dp", "fsdp")) or ():
        divisor *= mesh.shape.get(name, 1)

    @jax.jit
    def eval_batch(params, batch, valid):
        logits = model.apply(params, batch["inputs"], train=False)
        hit = (logits.argmax(-1) == batch["labels"]) & valid
        return hit.sum()

    def evaluate(params, dataset):
        correct, total = 0, 0
        for batch in dataset.epoch(0):
            n = len(batch["labels"])
            pad = (-n) % divisor
            if pad:
                batch = {k: np.concatenate(
                    [v, np.repeat(v[-1:], pad, axis=0)])
                    for k, v in batch.items()}
            valid = np.arange(n + pad) < n
            batch = jax.device_put(batch, batch_sharding)
            valid = jax.device_put(jnp.asarray(valid), batch_sharding)
            correct += int(eval_batch(params, batch, valid))
            total += n
        return correct / max(total, 1)

    return evaluate


# --strategy keys whose values are selectors, not mesh-axis sizes.
_STRATEGY_STR_KEYS = ("pp_schedule",)


def parse_strategy(raw):
    """``--strategy`` accepts JSON or ``axis:size[,axis:size...]``.

    Values parse as ints except the selector keys (e.g.
    ``pp:2,pp_schedule:gpipe``), which stay strings."""
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
    except ValueError:
        pass
    else:
        if not isinstance(parsed, dict):
            raise SystemExit(
                f"--strategy: expected an object of axis sizes, got "
                f"{raw!r}; use JSON ('{{\"dp\": 2, \"ep\": 4}}') or "
                '"dp:2,ep:4"')
        return parsed
    out = {}
    for part in raw.split(","):
        part = part.strip()
        sep = ":" if ":" in part else ("=" if "=" in part else None)
        if not sep:
            raise SystemExit(
                f"--strategy: cannot parse {raw!r}; use JSON "
                '(\'{"dp": 2, "ep": 4}\') or "dp:2,ep:4"')
        name, _, value = part.partition(sep)
        name = name.strip()
        if name in _STRATEGY_STR_KEYS:
            out[name] = value.strip()
            continue
        try:
            out[name] = int(value)
        except ValueError:
            raise SystemExit(
                f"--strategy: axis size {value!r} is not an integer "
                f"in {raw!r}") from None
    return out


def main(argv=None) -> int:
    try:
        return _main(argv)
    finally:
        from .ops.attention import deactivate_sequence_parallel

        deactivate_sequence_parallel()


def _main(argv=None) -> int:
    args = build_argparser().parse_args(argv)

    import jax

    platform = os.environ.get("POLYAXON_TPU_PLATFORM")
    if args.cpu:
        platform = "cpu"
    if platform:
        # The TPU-tunnel plugin ignores JAX_PLATFORMS; the live config
        # works when set before first backend use.
        jax.config.update("jax_platforms", platform)

    # 0b. persistent XLA compilation cache: tuner sweeps and gang
    #     restarts re-run the same program shapes — only the first run
    #     should pay the compile (dominant per-trial cost in the sweep
    #     bench).  Opt out with PTPU_COMPILATION_CACHE=0.
    if os.environ.get("PTPU_COMPILATION_CACHE", "1") != "0" and \
            not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        from .config import home_dir

        cache_dir = os.path.join(home_dir(), "xla-cache")
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # Persist even sub-second compiles (tiny sweep trials are
            # exactly the repeated-compile workload) and bound the
            # directory with LRU eviction so long-lived agent hosts
            # don't grow it forever.
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_compilation_cache_max_size",
                              4 * 1024 ** 3)
        except Exception:  # noqa: BLE001 - cache is an optimization
            pass

    # 1. multi-host bootstrap from injected topology env (no-op when the
    #    run is single-process).
    from .parallel.bootstrap import initialize_from_env

    topology = initialize_from_env()

    # 1b. slice health gate (SURVEY 5.3): prove the fabric computes and
    #     communicates BEFORE restoring checkpoints / tracing the step.
    #     Unhealthy -> exit nonzero so the operator reschedules the gang.
    if topology is not None and topology.is_distributed:
        from .parallel.health import check_slice_health

        health = check_slice_health(
            timeout_s=float(os.environ.get(
                "PTPU_SLICE_HEALTH_TIMEOUT", "120")))
        print(f"slice health: {health.detail}", flush=True)
        if not health.ok:
            raise SystemExit(f"unhealthy slice: {health.detail}")

    import jax.numpy as jnp
    import numpy as np

    from .checkpoint import CheckpointManager
    from .models.registry import get_model
    from .parallel import MeshSpec, build_mesh, make_train_step
    from . import tracking

    # 2. mesh from the strategy spec: JSON ('{"dp": 2, "ep": 4}') or the
    # compact axis list ("dp:2,ep:4" / "dp=2,ep=4").
    strategy_raw = args.strategy or os.environ.get("PTPU_STRATEGY")
    strategy = parse_strategy(strategy_raw)
    # pp_schedule is a schedule selector (1f1b | gpipe), not a mesh axis.
    mesh = build_mesh(MeshSpec.from_dict(
        {k: v for k, v in strategy.items() if k != "pp_schedule"}))
    n_chips = mesh.devices.size

    # Unsupported compositions fail LOUDLY and FAST — before datasets
    # and (potentially multi-GiB) param init, and not with a nested
    # shard_map trace error 40 frames deep: sp routes attention through
    # its own shard_map and ep all-to-alls inside the MoE layer —
    # neither composes with the pipeline's manual pp axis yet (pp x tp
    # and pp x dp/fsdp do).
    if mesh.shape.get("pp", 1) > 1:
        for bad_axis in ("sp", "ep"):
            if mesh.shape.get(bad_axis, 1) > 1:
                raise SystemExit(
                    f"strategy combines pp>1 with {bad_axis}>1, which "
                    f"is not supported: pipeline stages compose with "
                    f"dp/fsdp (batch) and tp (tensor) axes only")

    # sp > 1: route every model's attention through ring/Ulysses
    # sequence parallelism for the whole run (activated before any jit
    # trace; main()'s wrapper deactivates on the way out so in-process
    # callers — tune workers, tests — never inherit stale routing).
    from .ops.attention import activate_sequence_parallel

    if mesh.shape.get("sp", 1) > 1:
        activate_sequence_parallel(mesh, args.sp_mode)

    spec = get_model(args.model)
    batch_size = args.batch_size or spec.default_batch_size
    data_axes = max(1, mesh.shape["dp"] * mesh.shape["fsdp"])
    # Pipelined runs split the batch into 2*pp microbatches, each of
    # which must still shard over the data axes.
    granularity = data_axes * 2 * mesh.shape["pp"] \
        if mesh.shape.get("pp", 1) > 1 else data_axes
    if batch_size % granularity:
        batch_size = granularity * max(1, batch_size // granularity)

    # Data defines the input shapes: init params from a dataset sample
    # (e.g. digits are 8x8 where the synthetic stand-in is 28x28).
    model_kw = _HF_MODEL_KW.get(args.model.split("-")[0], {}) \
        if args.init_hf else {}
    model = spec.make_model(**model_kw)
    train_ds, eval_ds = make_datasets(args, spec, batch_size,
                                      model=model)
    sample = train_ds.sample(2)
    # --init-hf replaces the params wholesale: don't pay a full random
    # init (a transient multi-GB allocation for the 1B models) just to
    # discard it.
    params = load_hf_init(args.model, model, args.init_hf) \
        if args.init_hf else \
        model.init(jax.random.PRNGKey(args.seed), sample["inputs"])
    loss_fn = spec.loss_fn(model)
    if mesh.shape.get("pp", 1) > 1:
        # strategy {pp: N}: route the block stack through the
        # collective-permute pipeline (VERDICT r1 #5).  Default
        # schedule is 1F1B (O(stages) activation memory via in-schedule
        # VJP — VERDICT r2 task 5); {pp_schedule: gpipe} selects the
        # autodiff GPipe scan.
        from .models.gpt2 import GPT2Block, GPT2Model
        from .models.llama import LlamaBlock, LlamaModel
        from .parallel.pipeline import (pipelined_lm_loss,
                                        pipelined_lm_loss_1f1b)

        if isinstance(model, GPT2Model) and model.cfg.scan_layers:
            pp_block = GPT2Block(model.cfg)
        elif isinstance(model, LlamaModel) and model.cfg.scan_layers:
            pp_block = LlamaBlock(model.cfg)
        else:
            raise SystemExit(
                "strategy pp>1 supports the scanned GPT-2 and Llama "
                f"families, not {args.model}")
        pp_sched = str(strategy.get("pp_schedule", "1f1b")).lower() \
            if isinstance(strategy, dict) else "1f1b"
        if pp_sched not in ("1f1b", "gpipe"):
            raise SystemExit(
                f"pp_schedule must be '1f1b' or 'gpipe', got "
                f"{pp_sched!r}")
        make_pp_loss = pipelined_lm_loss if pp_sched == "gpipe" \
            else pipelined_lm_loss_1f1b
        loss_fn = make_pp_loss(model, pp_block, mesh)
    step_fn = make_train_step(
        loss_fn, make_optimizer(args.optimizer, args.lr),
        mesh, grad_accum=args.grad_accum, donate=True)
    state = step_fn.init_state(params)

    # 3. tracking: attaches to the managed run (env) or creates one.
    run = tracking.init(name=f"train-{args.model}")
    run.log_inputs(model=args.model, lr=args.lr, batch_size=batch_size,
                   strategy=strategy or {"dp": -1},
                   n_chips=int(n_chips),
                   backend=jax.default_backend())

    # 4. checkpointing with auto-resume.
    ckpt = CheckpointManager(run_uuid=run.client.run_uuid)
    start_step = 0
    if args.resume:
        state, restored = ckpt.restore_or_init(state)
        start_step = int(restored or 0)
        if restored is not None:
            # Stdout, not just the logger: the restart/preemption story
            # is diagnosed from pod logs.
            print(f"resuming from checkpoint step {start_step}",
                  flush=True)
    ckpt.install_preemption_hook(lambda: state,
                                 lambda: int(state["step"]))

    synthetic = (args.dataset or
                 ("npy" if args.data_dir else "synthetic")) == "synthetic"
    steps_per_epoch = args.steps_per_epoch or \
        (100 if synthetic else train_ds.steps_per_epoch)
    total_steps = args.steps or args.epochs * steps_per_epoch
    from .data import prefetch_to_device

    # Endless reshuffled-per-epoch stream, RESUMED at the restored
    # step: the datasets are deterministic in (seed, epoch), so a
    # preemption-resumed run continues through the schedule exactly
    # where the crashed run stopped instead of replaying batch 0
    # (data._EpochIterable.epochs).
    batches = train_ds.epochs(None, start_step=start_step)
    if args.prefetch:
        batches = prefetch_to_device(batches, step_fn.batch_sharding,
                                     depth=args.prefetch)
    rng = jax.random.PRNGKey(args.seed)

    target = parse_target_metric(args.target_metric)
    evaluate = make_eval_fn(model, mesh, step_fn.batch_sharding) \
        if eval_ds is not None else None
    # Evals ride the logging steps (metrics are only published there);
    # snap --eval-every up to the next log step so no eval is lost to
    # the log cadence.
    eval_steps = set()
    if evaluate and args.eval_every:
        for due in range(args.eval_every, total_steps + 1,
                         args.eval_every):
            snapped = -(-due // args.log_every) * args.log_every
            eval_steps.add(min(snapped, total_steps))

    unit = "tok" if sample["inputs"].ndim == 2 else "img"
    per_batch = batch_size * sample["inputs"].shape[1] \
        if unit == "tok" else batch_size

    # AOT-compile off the timed path so the first logged block measures
    # steps, not trace + XLA compile (TrainStep.precompile — the
    # supported AOT surface, VERDICT r2 weak #6).
    first = next(batches)
    if args.prefetch == 0:
        first = jax.device_put(first, step_fn.batch_sharding)
    try:
        _, compile_s = step_fn.precompile(state, first,
                                          jax.random.split(rng)[1])
        run.log_metrics(step=start_step, compile_s=round(compile_s, 2))
        print(f"compiled train step in {compile_s:.1f}s", flush=True)
    except Exception as e:  # fall back to trace-on-first-call
        print(f"precompile skipped ({type(e).__name__}: {e}); "
              "first step will trace", flush=True)
    batches = itertools.chain([first], batches)

    last_metrics: Dict[str, Any] = {}
    t_block = time.perf_counter()
    block_start = start_step
    for step in range(start_step, total_steps):
        if args.profile_at and step == args.profile_at:
            run.start_profiler_trace()
        rng, step_rng = jax.random.split(rng)
        batch = next(batches)
        if args.prefetch == 0:
            batch = jax.device_put(batch, step_fn.batch_sharding)
        state, metrics = step_fn(state, batch, step_rng)
        if args.profile_at and step + 1 == args.profile_at + \
                args.profile_steps:
            jax.block_until_ready(state)
            run.stop_profiler_trace(step=step + 1)
        if ckpt.preempt_requested:
            # SIGTERM landed while the bound state was donated into the
            # in-flight step; save the fresh output state and exit within
            # the operator's grace period (checkpoint.py).
            ckpt.save(step + 1, state, force=True)
            ckpt.wait()
            print("preempted: checkpoint flushed, exiting", flush=True)
            break
        if args.checkpoint_every and (step + 1) % args.checkpoint_every == 0:
            ckpt.save(step + 1, state)  # async; off the step path
        if (step + 1) % args.log_every == 0 or step + 1 == total_steps:
            metrics = {k: float(v) for k, v in metrics.items()}
            dt = time.perf_counter() - t_block
            done = step + 1 - block_start
            throughput = per_batch * done / dt / n_chips
            metrics[f"{unit}_per_sec_per_chip"] = round(throughput, 2)
            if (step + 1) in eval_steps:
                metrics["eval_accuracy"] = evaluate(state["params"],
                                                    eval_ds)
            run.log_metrics(step=step + 1, **metrics)
            print(f"step {step + 1}/{total_steps} "
                  + " ".join(f"{k}={v:.4g}" for k, v in metrics.items()),
                  flush=True)
            last_metrics = metrics
            t_block = time.perf_counter()
            block_start = step + 1
            if target and target[0] in metrics and \
                    target_reached(metrics[target[0]], target):
                print(f"target {target[0]}{target[2]}{target[1]} reached",
                      flush=True)
                break

    # A profile window reaching past the last step still finalizes.
    run.stop_profiler_trace(step=int(state["step"]))
    ckpt.save(int(state["step"]), state, force=True)
    ckpt.wait()
    ckpt.close()
    if evaluate:
        final_eval = evaluate(state["params"], eval_ds)
        run.log_metrics(step=int(state["step"]),
                        eval_accuracy=final_eval)
        last_metrics["eval_accuracy"] = final_eval
        print(f"final eval_accuracy={final_eval:.4f}", flush=True)
    for key, value in last_metrics.items():
        if key in ("accuracy", "loss", "perplexity", "eval_accuracy"):
            run.log_outputs(**{key: value})
    run.end("succeeded")
    if topology and topology.is_distributed:
        jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
