"""Hook execution: post-run actions (notify / follow-up operations).

Parity: reference ``V1Hook`` + notifier kind (SURVEY.md 2.3; notifier
auxiliaries).  After a run reaches a terminal status the executor calls
``run_hooks``: each hook whose ``trigger`` matches fires — connection
hooks emit a notification through the connection (webhook/slack POST
with a short timeout; always recorded as a notification artifact so
air-gapped clusters still get an audit trail), hub_ref hooks are
recorded for the scheduler to materialize.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, List, Optional

from ..lifecycle import V1Statuses

logger = logging.getLogger(__name__)

_TRIGGER_STATUSES = {
    "succeeded": {V1Statuses.SUCCEEDED},
    "failed": {V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED},
    "stopped": {V1Statuses.STOPPED},
}


def trigger_matches(trigger: Optional[str], status: str) -> bool:
    if not trigger or trigger == "done":
        return status in V1Statuses.DONE
    return status in _TRIGGER_STATUSES.get(trigger, set())


_COND_OPS = [
    ("==", lambda a, b: a == b),
    ("!=", lambda a, b: a != b),
    (">=", lambda a, b: a >= b),
    ("<=", lambda a, b: a <= b),
    (">", lambda a, b: a > b),
    ("<", lambda a, b: a < b),
]


def _cond_operand(token: str, ctx: Dict[str, Any]) -> Any:
    token = token.strip()
    try:
        return json.loads(token)  # numbers, booleans, quoted strings
    except ValueError:
        pass
    from ..compiler.templates import TemplateError, _lookup

    try:
        return _lookup(token, ctx)
    except TemplateError:
        if "." in token:
            # Dotted tokens are context paths; a missing path must make
            # the condition False, not silently become a string literal
            # ('outputs.accuracy != 0' on a run with no outputs).
            raise
        return token  # bare string literal (status == succeeded)


def evaluate_condition(condition: Optional[str],
                       ctx: Dict[str, Any]) -> bool:
    """Minimal safe condition language: ``lhs OP rhs`` (optionally
    ``{{ ... }}``-wrapped) over the run context; a bare path is truthy-
    tested.  Unknown paths / type errors evaluate False (a hook must
    never crash a finished run)."""
    if not condition:
        return True
    expr = condition.strip()
    if expr.startswith("{{") and expr.endswith("}}"):
        expr = expr[2:-2].strip()
    try:
        for op, fn in _COND_OPS:
            if op in expr:
                lhs, _, rhs = expr.partition(op)
                return bool(fn(_cond_operand(lhs, ctx),
                               _cond_operand(rhs, ctx)))
        return bool(_cond_operand(expr, ctx))
    except Exception as e:  # noqa: BLE001 - conditions are best-effort
        logger.warning("hook condition %r failed to evaluate: %s",
                       condition, e)
        return False


def _notify_connection(conn, payload: Dict[str, Any],
                       timeout: float = 5.0) -> str:
    """POST the payload to webhook-ish connections; returns delivery
    state (sent/skipped/error:...)."""
    from ..connections import ConnectionKind

    if conn.kind not in (ConnectionKind.SLACK, ConnectionKind.WEBHOOK):
        return "skipped"
    url = conn.typed_schema().url
    try:
        import urllib.request

        if conn.kind == ConnectionKind.SLACK:
            body = {"text": payload.get("message", ""),
                    "attachments": [{"fields": [
                        {"title": k, "value": str(v), "short": True}
                        for k, v in payload.items() if k != "message"]}]}
        else:
            body = payload
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout):
            pass
        return "sent"
    except Exception as e:  # noqa: BLE001 - notification must not fail runs
        logger.warning("notification to %s failed: %s", conn.name, e)
        return f"error: {e}"


def run_hooks(compiled, record: Dict[str, Any], store,
              catalog=None) -> List[Dict[str, Any]]:
    """Fire matching hooks; returns the notification records written."""
    hooks = getattr(compiled, "hooks", None) or []
    if not hooks:
        return []
    status = record.get("status")
    if catalog is None:
        from ..connections import ConnectionCatalog

        catalog = ConnectionCatalog.load()

    cond_ctx = {
        "outputs": record.get("outputs") or {},
        "inputs": record.get("inputs") or {},
        "status": status,
        "globals": record,
    }
    fired: List[Dict[str, Any]] = []
    for hook in hooks:
        if not trigger_matches(hook.trigger, status):
            continue
        if not evaluate_condition(hook.conditions, cond_ctx):
            continue
        payload = {
            "message": f"Run {record.get('name')} ({record['uuid']}) "
                       f"finished with status {status}",
            "uuid": record["uuid"],
            "name": record.get("name"),
            "project": record.get("project"),
            "status": status,
            "duration": record.get("duration"),
            "outputs": record.get("outputs") or {},
            "ts": time.time(),
        }
        entry: Dict[str, Any] = {"trigger": hook.trigger or "done",
                                 "payload": payload}
        if hook.connection:
            entry["connection"] = hook.connection
            try:
                conn = catalog.get(hook.connection)
                entry["delivery"] = _notify_connection(conn, payload)
            except KeyError as e:
                entry["delivery"] = f"error: {e}"
        if hook.hub_ref:
            # Follow-up operation: recorded; the scheduler/CLI can
            # materialize it (hub resolution is deployment-specific).
            entry["hub_ref"] = hook.hub_ref
            entry["params"] = hook.params or {}
        fired.append(entry)

    if fired:
        store.append_events(record["uuid"], "notification", "hooks",
                            fired)
    return fired
