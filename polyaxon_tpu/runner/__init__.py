"""Executors: turn compiled operations into real processes.

Local mode (SURVEY.md §7 step 4 — the minimum end-to-end slice) executes
components as host subprocesses with the same env-injection contract the
k8s converter uses in-cluster, so a spec runs identically under
``ptpu run`` on a laptop and under the operator on a TPU pod-slice.
"""

from .local import ExecutionError, LocalExecutor
