"""The agent: claims queued runs from the control plane and executes them.

Parity: reference agent service (SURVEY.md 2.9, L3) — polls the queue,
invokes the converter, applies resources, watches status, reports back,
cleans up.  Backends:

- ``LocalBackend``    — executes on this host via ``LocalExecutor``
  (subprocess per replica with the full PTPU_* env); the single-box
  deployment and the test harness.
- ``ManifestBackend`` — converts to ``Operation`` CRs and writes them to
  a cluster directory; the operator (C++, ``operator/``) reconciles them
  into pods and writes status files back.  The same file protocol an
  apply-to-k8s transport implements with the API server.

DAG / matrix (tuner) kinds are controller runs: the agent executes the
controller in-process, and the controller creates child runs back
through the store — each child is then claimed and executed like any
other run.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..client.store import FileRunStore
from ..flow import V1Operation
from ..k8s import (ConverterConfig, cluster_ip_service, convert,
                   headless_service)
from ..lifecycle import V1Statuses, is_done
from .local import LocalExecutor

logger = logging.getLogger(__name__)


@dataclass
class _Active:
    run_uuid: str
    handle: Any
    backend: "Backend"
    ttl: Optional[int] = None
    done_at: Optional[float] = None
    endpoint_recorded: bool = False


class Backend:
    """Execution transport for one claimed run."""

    def submit(self, record: Dict[str, Any],
               operation: V1Operation) -> Any:  # -> handle
        raise NotImplementedError

    def check(self, handle: Any) -> Optional[str]:
        """Current terminal status (succeeded/failed/stopped) or None."""
        raise NotImplementedError

    def stop(self, handle: Any) -> None:
        raise NotImplementedError

    def cleanup(self, handle: Any) -> None:
        pass


class LocalBackend(Backend):
    """Runs the operation on this host in a supervised thread."""

    def __init__(self, store: FileRunStore, project: str = "default"):
        self.store = store
        self.project = project

    def submit(self, record, operation):
        executor = LocalExecutor(store=self.store,
                                 project=record.get("project")
                                 or self.project)
        state = {"status": None}

        def work():
            try:
                final = executor.run_operation(operation,
                                               run_uuid=record["uuid"])
                state["status"] = final.get("status")
            except Exception as e:  # noqa: BLE001 - terminal supervision
                logger.exception("local execution failed")
                state["status"] = V1Statuses.FAILED
                self.store.set_status(record["uuid"], V1Statuses.FAILED,
                                      reason="AgentLocalBackend",
                                      message=str(e), force=True)
            finally:
                self._relay_logs(record["uuid"])

        thread = threading.Thread(target=work, daemon=True)
        thread.start()
        return (thread, state)

    def _relay_logs(self, run_uuid: str) -> None:
        """Remote store: push locally-written replica logs up to the
        control plane so `ops logs` serves them."""
        if not getattr(self.store, "host", None):
            return  # file store: logs are already in place
        try:
            if self.store.read_logs(run_uuid):
                return  # control plane shares the home tree; already there
        except Exception:  # noqa: BLE001 - relay is best-effort
            pass
        logs_dir = os.path.dirname(self.store.logs_path(run_uuid))
        if not os.path.isdir(logs_dir):
            return
        for fname in sorted(os.listdir(logs_dir)):
            if not fname.endswith(".log"):
                continue
            try:
                with open(os.path.join(logs_dir, fname)) as f:
                    text = f.read()
                if text:
                    self.store.append_log(run_uuid, text,
                                          replica=fname[:-4])
            except OSError:
                continue

    def check(self, handle):
        thread, state = handle
        if thread.is_alive():
            return None
        return state["status"] or V1Statuses.FAILED

    def stop(self, handle):
        pass  # cooperative: executor reacts to the run's `stopping` status


def convert_record(record: Dict[str, Any], operation: V1Operation,
                   store, config: ConverterConfig):
    """Resolve + convert one claimed run into (CR, [services]).

    Shared by every cluster transport (file protocol, kube API): the
    manifests are identical; only the apply mechanism differs."""
    from ..compiler import resolve

    from .joins import get_joins, resolve_joins

    join_values = None
    if get_joins(operation) and store is not None:
        join_values = resolve_joins(operation, store,
                                    project=record.get("project"))
    compiled = resolve(operation, run_uuid=record["uuid"],
                       project=record.get("project"),
                       join_values=join_values)
    cr = convert(compiled, record["uuid"], record.get("project"), config)
    services = [svc for svc in (headless_service(cr),
                                cluster_ip_service(cr)) if svc]
    return cr, services


class ManifestBackend(Backend):
    """File-protocol cluster transport.

    Layout under ``cluster_dir``:
        operations/<name>.json   CRs this agent applies
        status/<name>.json       {"phase": ..., "message": ...} from the
                                 operator
    """

    _PHASES = {
        "Succeeded": V1Statuses.SUCCEEDED,
        "Failed": V1Statuses.FAILED,
        "Stopped": V1Statuses.STOPPED,
    }

    def __init__(self, cluster_dir: str,
                 config: Optional[ConverterConfig] = None,
                 store: Optional[FileRunStore] = None):
        """``store`` enables join resolution at submit time; the Agent
        fills it in when absent."""
        self.cluster_dir = cluster_dir
        self.config = config or ConverterConfig()
        self.store = store
        os.makedirs(os.path.join(cluster_dir, "operations"), exist_ok=True)
        os.makedirs(os.path.join(cluster_dir, "status"), exist_ok=True)

    def submit(self, record, operation):
        cr, services = convert_record(record, operation, self.store,
                                      self.config)
        name = cr["metadata"]["name"]
        path = os.path.join(self.cluster_dir, "operations", f"{name}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"operation": cr, "services": services}, f, indent=1)
        os.replace(tmp, path)
        return name

    def check(self, handle):
        status = self.read_status(handle)
        if status is None:
            return None
        return self._PHASES.get(status.get("phase"))

    def read_status(self, handle) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.cluster_dir, "status", f"{handle}.json")
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except ValueError:
            return None

    def stop(self, handle):
        path = os.path.join(self.cluster_dir, "operations",
                            f"{handle}.json")
        try:
            with open(path) as f:
                doc = json.load(f)
            doc["operation"]["spec"]["stopped"] = True
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except (OSError, ValueError):
            pass

    def cleanup(self, handle):
        for sub in ("operations", "status"):
            try:
                os.remove(os.path.join(self.cluster_dir, sub,
                                       f"{handle}.json"))
            except OSError:
                pass


class KubeBackend(Backend):
    """kube-apiserver transport (VERDICT r1 #7).

    Applies converted Operation CRs + headless Services through the k8s
    REST API (SURVEY.md §3.1 step 8: converter output → k8s API); the
    operator — ours in ``--kube-api`` mode, reconciling the same CRD
    ``deploy.py`` registers — turns them into pods and writes
    ``.status`` back, which ``read_status``/``check`` poll."""

    _PHASES = ManifestBackend._PHASES

    def __init__(self, client=None,
                 config: Optional[ConverterConfig] = None,
                 store: Optional[FileRunStore] = None):
        from ..k8s.kubeclient import KubeClient

        self.client = client or KubeClient()
        # CR metadata.namespace must match the namespace objects are
        # POSTed to — a real apiserver 400s on mismatch (the converter
        # default is only right for the default deployment namespace).
        self.config = config or ConverterConfig(
            namespace=self.client.namespace)
        self.store = store

    def submit(self, record, operation):
        from ..k8s.kubeclient import KubeApiError, OPERATIONS_GROUP

        cr, services = convert_record(record, operation, self.store,
                                      self.config)
        name = cr["metadata"]["name"]
        try:
            self.client.create("operations", cr, group=OPERATIONS_GROUP)
        except KubeApiError as e:
            if e.code != 409:  # already applied (agent restart): adopt
                raise
        for svc in services:
            try:
                self.client.create("services", svc)
            except KubeApiError as e:
                if e.code != 409:
                    raise
        return name

    def read_status(self, handle) -> Optional[Dict[str, Any]]:
        from ..k8s.kubeclient import KubeApiError, OPERATIONS_GROUP

        try:
            obj = self.client.get("operations", handle,
                                  group=OPERATIONS_GROUP)
        except KubeApiError:
            return None
        return obj.get("status") or None

    def check(self, handle):
        status = self.read_status(handle)
        if status is None:
            return None
        return self._PHASES.get(status.get("phase"))

    def stop(self, handle):
        from ..k8s.kubeclient import KubeApiError, OPERATIONS_GROUP

        try:
            self.client.patch("operations", handle,
                              {"spec": {"stopped": True}},
                              group=OPERATIONS_GROUP)
        except KubeApiError:
            pass

    def cleanup(self, handle):
        from ..k8s.kubeclient import KubeApiError, OPERATIONS_GROUP

        for plural, group, name in (("operations", OPERATIONS_GROUP,
                                     handle),
                                    ("services", "", f"{handle}-hs"),
                                    ("services", "", handle)):
            try:
                self.client.delete(plural, name, group=group)
            except KubeApiError:
                pass


class Agent:
    """Queue-polling loop supervising claimed runs to completion."""

    def __init__(
        self,
        plane,  # ControlPlane (in-process) or ApiRunStore (remote agent)
        backend: Optional[Backend] = None,
        name: str = "agent-0",
        poll_interval: float = 0.2,
        max_concurrent: int = 8,
        queues: Optional[list] = None,
    ):
        self.plane = plane
        # Both expose .claim(); ControlPlane wraps the store, ApiRunStore
        # IS the (remote) store.
        self.store = getattr(plane, "store", plane)
        self.backend = backend or LocalBackend(self.store)
        self.name = name
        self.poll_interval = poll_interval
        self.max_concurrent = max_concurrent
        # Restrict this agent to named queues (None = serve everything,
        # including unqueued runs).
        self.queues = list(queues) if queues else None
        # Backends that can resolve joins need store access.
        if getattr(self.backend, "store", True) is None:
            self.backend.store = self.store
        self.active: Dict[str, _Active] = {}
        self._stop = threading.Event()

    # -- lifecycle -------------------------------------------------------

    def stop(self):
        self._stop.set()

    def run_forever(self):
        while not self._stop.is_set():
            progressed = self.tick()
            if not progressed:
                self._stop.wait(self.poll_interval)

    def tick(self) -> bool:
        """One scheduling round; returns True if anything happened."""
        progressed = self._reap()
        # Finished runs merely awaiting TTL cleanup don't hold a slot.
        live = sum(1 for a in self.active.values() if a.done_at is None)
        if live < self.max_concurrent:
            record = self.plane.claim(self.name, queues=self.queues)
            if record:
                self._launch(record)
                progressed = True
        return progressed

    # -- internals -------------------------------------------------------

    def _launch(self, record: Dict[str, Any]) -> None:
        run_uuid = record["uuid"]
        try:
            operation = V1Operation.from_dict(record["content"])
        except Exception as e:  # content written by client; may be bad
            self.store.set_status(run_uuid, V1Statuses.FAILED,
                                  reason="AgentParseError", message=str(e),
                                  force=True)
            return
        try:
            handle = self.backend.submit(record, operation)
        except Exception as e:  # noqa: BLE001 - submission is a boundary
            logger.exception("submit failed for %s", run_uuid)
            self.store.set_status(run_uuid, V1Statuses.FAILED,
                                  reason="AgentSubmitError", message=str(e),
                                  force=True)
            return
        termination = (record.get("content") or {}).get("termination") or {}
        self.active[run_uuid] = _Active(
            run_uuid=run_uuid, handle=handle, backend=self.backend,
            ttl=termination.get("ttl"))
        self.store.set_status(run_uuid, V1Statuses.STARTING,
                              reason="AgentSubmit")

    def _reap(self) -> bool:
        progressed = False
        now = time.time()
        for run_uuid, active in list(self.active.items()):
            if active.done_at is not None:
                # Finished: only TTL cleanup remains (no store polling,
                # no progress claim — the loop must be able to sleep).
                if active.ttl is None or now - active.done_at >= active.ttl:
                    active.backend.cleanup(active.handle)
                    del self.active[run_uuid]
                    progressed = True
                continue
            # user/CLI requested stop?
            try:
                current = self.store.get_run(run_uuid).get("status")
            except Exception:
                current = None
            if current == V1Statuses.STOPPING:
                active.backend.stop(active.handle)
            if hasattr(active.backend, "read_status"):
                # One status read per tick serves both endpoint discovery
                # and the terminal-phase check.
                status_doc = active.backend.read_status(active.handle)
                endpoints = (status_doc or {}).get("endpoints")
                if endpoints and not active.endpoint_recorded:
                    active.endpoint_recorded = True
                    try:
                        self.store.update_run(
                            run_uuid,
                            meta_info={"endpoint": endpoints[0],
                                       "endpoints": endpoints})
                    except Exception:  # noqa: BLE001 - metadata only
                        pass
                terminal = (active.backend._PHASES.get(
                    status_doc.get("phase")) if status_doc else None)
            else:
                terminal = active.backend.check(active.handle)
            if terminal is None:
                continue
            progressed = True
            active.done_at = now
            if not is_done(current):
                self.store.set_status(run_uuid, terminal,
                                      reason="AgentReap", force=True)
            if active.ttl is None:
                active.backend.cleanup(active.handle)
                del self.active[run_uuid]
        return progressed
