"""DAG execution: topological scheduling of member operations.

Parity: reference DAG runtime (SURVEY.md 2.4 ``V1Dag``): edges come from
explicit ``dependencies`` plus implicit ``params.ref == ops.<name>`` IO
references; ``concurrency`` bounds parallel ops; per-op ``trigger``
policies gate on upstream outcomes; failures propagate as
``upstream_failed`` unless the trigger tolerates them.
"""

from __future__ import annotations

import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Dict, List, Optional, Set

from ..flow import V1Component, V1Operation
from ..lifecycle import V1Statuses


class DagError(RuntimeError):
    pass


class DagStopped(RuntimeError):
    """A member run was deliberately stopped; the dag finalizes as stopped."""


def _op_from_entry(entry: Any, components: Dict[str, V1Component]) -> V1Operation:
    if isinstance(entry, V1Operation):
        op = entry
    elif isinstance(entry, dict):
        op = V1Operation.from_dict(entry)
    else:
        raise DagError(f"Bad dag operation entry: {entry!r}")
    if op.component is None and op.dag_ref:
        comp = components.get(op.dag_ref)
        if comp is None:
            raise DagError(f"dagRef {op.dag_ref!r} matches no dag component")
        op = op.model_copy(update={"component": comp, "dag_ref": None})
    if op.component is None:
        raise DagError(
            f"Dag operation {op.name!r} has no component (inline or dagRef)"
        )
    return op


class DagRunner:
    def __init__(self, executor, compiled, pipeline_uuid: str):
        self.executor = executor
        self.pipeline_uuid = pipeline_uuid
        dag = compiled.run
        components = {}
        for centry in dag.components or []:
            comp = (centry if isinstance(centry, V1Component)
                    else V1Component.from_dict(centry))
            components[comp.name] = comp
        self.ops: Dict[str, V1Operation] = {}
        for entry in dag.operations or []:
            op = _op_from_entry(entry, components)
            if not op.name:
                raise DagError("Every dag operation needs a name")
            if op.name in self.ops:
                raise DagError(f"Duplicate dag operation name {op.name!r}")
            self.ops[op.name] = op
        self.concurrency = dag.concurrency or 4
        self.edges: Dict[str, Set[str]] = {name: set() for name in self.ops}
        for name, op in self.ops.items():
            for dep in op.dependencies or []:
                if dep not in self.ops:
                    raise DagError(
                        f"Operation {name!r} depends on unknown op {dep!r}"
                    )
                self.edges[name].add(dep)
            for param in (op.params or {}).values():
                if param.ref and param.ref.startswith("ops."):
                    dep = param.ref[len("ops."):]
                    if dep not in self.ops:
                        raise DagError(
                            f"Operation {name!r} references unknown op {dep!r}"
                        )
                    self.edges[name].add(dep)
        self._check_cycles()
        self.results: Dict[str, Dict[str, Any]] = {}
        self.statuses: Dict[str, str] = {}
        self._lock = threading.Lock()

    def _check_cycles(self) -> None:
        seen: Dict[str, int] = {}

        def visit(node: str, stack: List[str]):
            state = seen.get(node, 0)
            if state == 1:
                cycle = stack[stack.index(node):] + [node]
                raise DagError(f"Dag cycle: {' -> '.join(cycle)}")
            if state == 2:
                return
            seen[node] = 1
            for dep in self.edges[node]:
                visit(dep, stack + [node])
            seen[node] = 2

        for node in self.edges:
            visit(node, [])

    # ------------------------------------------------------------------

    def _upstream_ok(self, name: str) -> Optional[bool]:
        """True=run, False=skip (None is unused; kept for clarity)."""
        op = self.ops[name]
        trigger = op.trigger or "all_succeeded"
        deps = self.edges[name]
        stats = [self.statuses[d] for d in deps]
        if trigger == "all_succeeded":
            return all(s == V1Statuses.SUCCEEDED for s in stats)
        if trigger == "all_failed":
            return all(s in (V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED)
                       for s in stats)
        if trigger == "all_done":
            return True
        if trigger == "one_succeeded":
            return any(s == V1Statuses.SUCCEEDED for s in stats)
        if trigger == "one_failed":
            return any(s in (V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED)
                       for s in stats)
        if trigger == "one_done":
            return bool(stats)
        raise DagError(f"Unknown trigger {trigger!r} on op {name!r}")

    def _run_one(self, name: str) -> str:
        op = self.ops[name]
        deps = self.edges[name]
        dag_values: Dict[str, Any] = {}
        for dep in deps:
            for key, value in self.results.get(dep, {}).items():
                dag_values.setdefault(key, value)
                dag_values[f"{dep}.{key}"] = value

        def ref_resolver(ref: str, key: str):
            if ref.startswith("ops."):
                dep = ref[len("ops."):]
                outputs = self.results.get(dep, {})
                if key not in outputs:
                    raise DagError(
                        f"Op {name!r} wants output {key!r} of {dep!r} but "
                        f"it only produced {sorted(outputs)}"
                    )
                return outputs[key]
            if ref.startswith("runs."):
                return self.executor.store.get_run(
                    ref[len("runs."):]).get("outputs", {}).get(key)
            raise DagError(f"Unsupported ref {ref!r}")

        record = self.executor.run_operation_with_refs(
            op, dag_values=dag_values, ref_resolver=ref_resolver,
            pipeline=self.pipeline_uuid,
        )
        with self._lock:
            self.results[name] = record.get("outputs", {}) or {}
        return record["status"]

    def execute(self) -> Dict[str, str]:
        remaining = set(self.ops)
        futures = {}
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            while remaining or futures:
                ready = [
                    n for n in list(remaining)
                    if self.edges[n] <= set(self.statuses)
                ]
                skipped_any = False
                for name in ready:
                    remaining.discard(name)
                    if not self._upstream_ok(name):
                        skip_status = (
                            V1Statuses.UPSTREAM_FAILED
                            if any(self.statuses[d] in
                                   (V1Statuses.FAILED,
                                    V1Statuses.UPSTREAM_FAILED)
                                   for d in self.edges[name])
                            else V1Statuses.SKIPPED
                        )
                        self.statuses[name] = skip_status
                        skipped_any = True
                        continue
                    futures[pool.submit(self._run_one, name)] = name
                if skipped_any:
                    # Skip decisions may have made more ops ready.
                    continue
                if not futures:
                    if remaining:
                        raise DagError(
                            f"Deadlock: {sorted(remaining)} never became ready"
                        )
                    break
                done, _ = wait(list(futures), return_when=FIRST_COMPLETED)
                for fut in done:
                    name = futures.pop(fut)
                    try:
                        self.statuses[name] = fut.result()
                    except Exception:
                        self.statuses[name] = V1Statuses.FAILED
        stopped = [n for n, s in self.statuses.items()
                   if s == V1Statuses.STOPPED]
        if stopped:
            raise DagStopped(f"Dag stopped: members {sorted(stopped)}")
        failed = [n for n, s in self.statuses.items()
                  if s in (V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED)]
        if failed:
            raise DagError(f"Dag finished with failures: {sorted(failed)}")
        return self.statuses
