"""Join resolution: query-based fan-in of upstream run values.

Parity: reference ``V1Join`` (SURVEY.md 2.3/2.11) — an operation
declaring ``joins`` collects, for each join param, a LIST of values
gathered from every run matching the join's query (tuner analyses,
ensemble/report steps).  Value expressions:

    outputs.<key>    the run's recorded output
    inputs.<key>     the run's resolved input
    globals.<field>  run record field (uuid, name, status, ...)
    artifacts.<sub>  path under the run's artifact tree
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class JoinError(ValueError):
    pass


def _extract(record: Dict[str, Any], expr: str, store) -> Any:
    if expr.startswith("outputs."):
        return (record.get("outputs") or {}).get(expr[len("outputs."):])
    if expr.startswith("inputs."):
        return (record.get("inputs") or {}).get(expr[len("inputs."):])
    if expr.startswith("globals."):
        field = expr[len("globals."):]
        if field == "run_artifacts_path":
            return store.artifacts_path(record["uuid"])
        if field == "run_outputs_path":
            return store.outputs_path(record["uuid"])
        return record.get(field) or record.get(
            {"run_uuid": "uuid", "run_name": "name"}.get(field, field))
    if expr.startswith("artifacts."):
        import os

        return os.path.join(store.artifacts_path(record["uuid"]),
                            expr[len("artifacts."):])
    if expr == "uuid":
        return record["uuid"]
    raise JoinError(
        f"Unknown join value expression {expr!r}; expected "
        "outputs.*/inputs.*/globals.*/artifacts.*")


def get_joins(operation) -> List[Any]:
    """Effective joins (joins are operation-level in the schema; the
    getattr keeps this robust if components ever grow them)."""
    if getattr(operation, "joins", None):
        return operation.joins
    component = getattr(operation, "component", None)
    return getattr(component, "joins", None) or []


def resolve_joins(operation, store,
                  project: Optional[str] = None) -> Dict[str, List[Any]]:
    """{param_name: [values across matched runs]} for every join."""
    out: Dict[str, List[Any]] = {}
    for join in get_joins(operation):
        records = store.list_runs(
            project=project, query=join.query, sort=join.sort,
            limit=join.limit, offset=join.offset or 0)
        for name, param in (join.params or {}).items():
            expr = param.value
            if not isinstance(expr, str):
                raise JoinError(
                    f"Join param {name!r} needs a string value "
                    f"expression, got {expr!r}")
            out[name] = [_extract(r, expr, store) for r in records]
    return out
