"""Local executor.

Execution semantics per run kind:

- ``job``:     one subprocess (command/args from the container spec).
- ``tpujob``/``tfjob``/``pytorchjob``/``mpijob``: N subprocesses — one per
  process in the normalized topology — each receiving the same PTPU_* env
  block the agent would inject in-cluster (coordinator on localhost).
  This is the "multi-node without a cluster" harness (SURVEY.md §4).
- ``dag``:     topological execution of member operations with concurrency.
- ``service``: spawned DETACHED in its own session (logs to the run's
  log file), gated on port readiness, left RUNNING; ``ops stop`` reaps
  it via the recorded pid (cli.main._reap_local_service).

Matrix operations are handled by the tuner controller
(``polyaxon_tpu.tune.controller``), which calls back into this executor
for each child run.
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..client import FileRunStore, RunClient
from ..client.run_client import ENV_PROJECT, ENV_RUN_UUID
from ..compiler import normalize, resolve
from ..compiler.resolver import make_compiled
from ..compiler.topology import ProcessTopology
from ..flow import V1Operation
from ..flow.run import RunKind
from ..lifecycle import V1Statuses


logger = logging.getLogger(__name__)


class ExecutionError(RuntimeError):
    pass


class StopRequested(Exception):
    """Raised inside _wait when ``ops stop`` flipped the run to stopping."""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _merge_container_env(env, container) -> None:
    """Overlay the container's literal env entries onto ``env`` (one
    place: job, distributed, and service spawns all share it)."""
    for e in (container.env or []):
        if e.value is not None:
            env[e.name] = str(e.value)


def _port_open(host: str, port: int, timeout: float = 0.5) -> bool:
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


class LocalExecutor:
    def __init__(self, store: Optional[FileRunStore] = None,
                 project: str = "default", stream_logs: bool = False):
        self.store = store or FileRunStore()
        self.project = project
        self.stream_logs = stream_logs

    # ------------------------------------------------------------------

    def create_run(self, operation: V1Operation,
                   pipeline: Optional[str] = None,
                   meta_info: Optional[Dict[str, Any]] = None) -> str:
        record = self.store.create_run(
            name=operation.name,
            project=self.project,
            description=operation.description,
            tags=operation.tags,
            content=operation.to_dict(),
            kind=getattr(operation.component.run, "kind", None)
            if operation.has_component else None,
            pipeline=pipeline,
            meta_info=meta_info,
        )
        return record["uuid"]

    def run_operation(
        self,
        operation: V1Operation,
        run_uuid: Optional[str] = None,
        matrix_values: Optional[Dict[str, Any]] = None,
        dag_values: Optional[Dict[str, Any]] = None,
        pipeline: Optional[str] = None,
        timeout: Optional[float] = None,
        ref_resolver=None,
    ) -> Dict[str, Any]:
        """Execute synchronously; returns the final run record."""
        if operation.matrix is not None:
            from ..tune.controller import TuneController

            run_uuid = run_uuid or self.create_run(operation,
                                                   pipeline=pipeline)
            controller = TuneController(self, operation, run_uuid)
            try:
                controller.execute()
            finally:
                # Sweep-level hooks fire once on the parent — also on
                # failure paths where execute() raises (the controller
                # has already set the terminal status).
                try:
                    self._finalize(run_uuid, make_compiled(operation))
                except Exception:  # noqa: BLE001 - hooks never mask
                    logger.debug("sweep finalize hooks failed",
                                 exc_info=True)
            return self.store.get_run(run_uuid)

        run_uuid = run_uuid or self.create_run(
            operation, pipeline=pipeline,
            meta_info={"matrix_values": matrix_values} if matrix_values else None,
        )
        try:
            from .joins import get_joins, resolve_joins

            join_values = None
            if get_joins(operation):
                join_values = resolve_joins(operation, self.store,
                                            project=self.project)
            compiled = resolve(
                operation, run_uuid=run_uuid, project=self.project,
                matrix_values=matrix_values, dag_values=dag_values,
                ref_resolver=ref_resolver, store_path=self.store.home,
                join_values=join_values,
            )
        except Exception as e:
            self.store.set_status(run_uuid, V1Statuses.FAILED,
                                  reason="CompilationError", message=str(e),
                                  force=True)
            # failed-trigger hooks still fire (hooks live on the raw
            # component; resolution never got that far)
            try:
                self._finalize(run_uuid, make_compiled(operation))
            except Exception:  # noqa: BLE001 - best effort on a failure
                logger.debug("failed-run finalize hooks failed",
                             exc_info=True)
            raise

        self.store.update_run(
            run_uuid,
            inputs=compiled.get_io_dict(),
        )
        self.store.set_status(run_uuid, V1Statuses.COMPILED,
                              reason="LocalExecutor")

        # Run memoization (SURVEY 2.3 V1Cache): with `cache: {}` declared
        # (and not disabled), an identical (component, inputs) run reuses
        # a prior SUCCEEDED run's outputs instead of re-executing.
        # Opt-in here (the reference defaults caching ON inside
        # pipelines; explicit declaration keeps local reuse predictable).
        cached = self._try_cache(run_uuid, operation, compiled)
        if cached is not None:
            return cached

        kind = compiled.run_kind
        termination = compiled.termination
        max_retries = (termination.max_retries if termination and
                       termination.max_retries else 0)
        timeout = timeout or (termination.timeout if termination else None)

        attempt = 0
        while True:
            try:
                if kind == RunKind.JOB:
                    self._run_job(run_uuid, compiled, timeout)
                elif kind in RunKind.DISTRIBUTED:
                    self._run_distributed(run_uuid, compiled, timeout)
                elif kind == RunKind.DAG:
                    self._run_dag(run_uuid, operation, compiled)
                elif kind == RunKind.SERVICE:
                    # Detached: the run stays RUNNING after we return;
                    # `ops stop` reaps it via the recorded pid.
                    self._run_service(run_uuid, compiled)
                    return self.store.get_run(run_uuid)
                else:
                    raise ExecutionError(
                        f"Run kind {kind!r} is not executable locally")
                break
            except StopRequested:
                self.store.set_status(run_uuid, V1Statuses.STOPPED,
                                      reason="StopRequested")
                return self._finalize(run_uuid, compiled)
            except ExecutionError as e:
                attempt += 1
                if attempt > max_retries:
                    self.store.set_status(run_uuid, V1Statuses.FAILED,
                                          reason="ExecutionError",
                                          message=str(e), force=True)
                    return self._finalize(run_uuid, compiled)
                self.store.set_status(run_uuid, V1Statuses.RETRYING,
                                      reason="Retry",
                                      message=f"attempt {attempt}", force=True)

        self.store.set_status(run_uuid, V1Statuses.SUCCEEDED,
                              reason="LocalExecutor")
        return self._finalize(run_uuid, compiled)

    def _cache_fingerprint(self, run_uuid: str, compiled, cache) -> str:
        """sha256 over the RESOLVED run section + inputs.

        Hashing the compiled run (not the raw component) means
        ``runPatch`` edits and matrix-templated commands fingerprint
        differently — two runs only match when the program they would
        execute is identical.  Run-scoped values (``{{ globals.* }}``
        paths embed the uuid) are masked so they don't defeat caching.
        ``cache.io_keys`` restricts which declared inputs participate;
        values already substituted into the command remain part of the
        run-section hash.
        """
        import hashlib

        inputs = compiled.get_io_dict()
        if cache.io_keys:
            inputs = {k: v for k, v in inputs.items()
                      if k in set(cache.io_keys)}
        run_dict = compiled.run.to_dict() if compiled.run is not None \
            else None
        blob = json.dumps({"run": run_dict, "inputs": inputs},
                          sort_keys=True, default=str)
        blob = blob.replace(run_uuid, "{run_uuid}")
        return hashlib.sha256(blob.encode()).hexdigest()

    def _try_cache(self, run_uuid: str, operation, compiled):
        """Cache lookup; returns the finished record on a hit, else None.

        A hit copies the prior run's outputs (record fields, the
        artifacts/outputs tree, AND tracked events — the tuner joins on
        metrics) and marks this run succeeded with
        ``meta_info.cache_hit``.
        """
        cache = compiled.cache
        if cache is None or cache.disable:
            return None

        fingerprint = self._cache_fingerprint(run_uuid, compiled, cache)
        self.store.update_run(run_uuid,
                              meta_info={"cache_fingerprint": fingerprint})

        now = time.time()
        # Newest-first, succeeded-only, bounded scan: the cache is an
        # optimization — missing a hit older than the window is fine,
        # reading every record in a huge store every run is not.
        candidates = self.store.list_runs(
            project=self.project,
            query=f"status:{V1Statuses.SUCCEEDED}",
            sort="-created_at", limit=500)
        for record in candidates:
            if record["uuid"] == run_uuid:
                continue
            meta = record.get("meta_info") or {}
            if meta.get("cache_fingerprint") != fingerprint:
                continue
            finished = record.get("finished_at") or record.get(
                "updated_at") or 0
            if cache.ttl and now - float(finished or 0) > cache.ttl:
                continue
            if self._copy_cached(record["uuid"], run_uuid):
                self.store.update_run(
                    run_uuid,
                    outputs=record.get("outputs") or {},
                    meta_info={"cache_hit": record["uuid"]})
                self.store.set_status(
                    run_uuid, V1Statuses.SUCCEEDED, reason="CacheHit",
                    message=f"reused outputs of {record['uuid']}",
                    force=True)
                return self._finalize(run_uuid, compiled)
        return None

    def _copy_cached(self, src_uuid: str, dst_uuid: str) -> bool:
        """Copy outputs + tracked events from a prior run; on failure
        (prior run deleted mid-copy) remove the debris and report a
        miss."""
        import shutil

        pairs = [
            (self.store.outputs_path(src_uuid),
             self.store.outputs_path(dst_uuid)),
            # events carry the metrics the tuner/queries join on
            (os.path.join(self.store.run_path(src_uuid), "events"),
             os.path.join(self.store.run_path(dst_uuid), "events")),
        ]
        try:
            for src, dst in pairs:
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
            return True
        except OSError:
            for _, dst in pairs:  # no phantom artifacts from a dead run
                shutil.rmtree(dst, ignore_errors=True)
                os.makedirs(dst, exist_ok=True)
            return False

    def _finalize(self, run_uuid: str, compiled) -> Dict[str, Any]:
        """Terminal bookkeeping: fire hooks, return the final record."""
        record = self.store.get_run(run_uuid)
        try:
            from .hooks import run_hooks

            run_hooks(compiled, record, self.store)
        except Exception:  # noqa: BLE001 - hooks never fail the run
            import logging

            logging.getLogger(__name__).exception("hook execution failed")
        return record

    def run_operation_with_refs(self, operation: V1Operation,
                                dag_values=None, ref_resolver=None,
                                pipeline: Optional[str] = None) -> Dict[str, Any]:
        """DAG-member entrypoint (outputs of upstream ops via refs)."""
        return self.run_operation(operation, dag_values=dag_values,
                                  ref_resolver=ref_resolver,
                                  pipeline=pipeline)

    # -- job ------------------------------------------------------------

    def _build_env(self, run_uuid: str, extra: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
        env = dict(os.environ)
        # The child must track against THIS executor's store: against the
        # API host when the store is remote (agent mode), otherwise the
        # local file store — a stale configured host would silently send
        # metrics elsewhere (breaking tuner joins in --eager mode).
        remote_host = getattr(self.store, "host", None)
        if remote_host:
            env["POLYAXON_TPU_HOST"] = remote_host
        else:
            env.pop("POLYAXON_TPU_HOST", None)
        env[ENV_RUN_UUID] = run_uuid
        env[ENV_PROJECT] = self.project
        env["POLYAXON_TPU_HOME"] = self.store.home
        env.update(extra or {})
        return env

    def _container_argv(self, container) -> List[str]:
        if container is None or (not container.command and not container.args):
            raise ExecutionError("Container has no command to execute")
        argv = list(container.command or [])
        argv += [str(a) for a in (container.args or [])]
        if len(argv) == 1 and " " in argv[0]:
            argv = shlex.split(argv[0])
        return argv

    def _spawn(self, run_uuid: str, argv: List[str], env: Dict[str, str],
               replica: str, cwd: Optional[str] = None) -> subprocess.Popen:
        log_path = self.store.logs_path(run_uuid, replica)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        proc = subprocess.Popen(
            argv, env=env, cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

        def pump():
            assert proc.stdout is not None
            with open(log_path, "a") as sink:
                for line in proc.stdout:
                    sink.write(line)
                    sink.flush()
                    if self.stream_logs:
                        sys.stdout.write(f"[{replica}] {line}")
                        sys.stdout.flush()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        proc._ptpu_pump = t  # joined before wait() returns
        return proc

    def _wait(self, run_uuid: str, procs: Dict[str, subprocess.Popen],
              timeout: Optional[float], poll_interval: float = 0.3) -> None:
        """Wait for all replicas; honors timeouts and cooperative stop
        (``ops stop`` flips the run to ``stopping``; we kill and finalize
        as ``stopped``)."""
        deadline = time.time() + timeout if timeout else None
        pending = dict(procs)
        failed: Dict[str, int] = {}
        last_status_check = 0.0
        while pending:
            for replica, proc in list(pending.items()):
                rc = proc.poll()
                if rc is not None:
                    proc._ptpu_pump.join(timeout=5)
                    del pending[replica]
                    if rc != 0:
                        failed[replica] = rc
            if not pending:
                break
            now = time.time()
            if deadline is not None and now >= deadline:
                self._kill_all(pending)
                raise ExecutionError(f"Run timed out after {timeout}s")
            if now - last_status_check >= poll_interval:
                last_status_check = now
                try:
                    status = self.store.get_run(run_uuid).get("status")
                except Exception:
                    status = None
                if status == V1Statuses.STOPPING:
                    self._kill_all(pending)
                    raise StopRequested()
            time.sleep(min(poll_interval, 0.05))
        if failed:
            detail = ", ".join(f"{r} exited {c}" for r, c in failed.items())
            raise ExecutionError(f"Process failure: {detail}")

    @staticmethod
    def _kill_all(procs: Dict[str, subprocess.Popen]) -> None:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()

    def _run_service(self, run_uuid: str, compiled) -> None:
        """Run a service kind DETACHED: spawn the container in its own
        session with logs sunk straight to the run's log file (no pipe
        — a pump thread would die with this process and block the
        service on a full pipe), gate on port readiness, record
        pid/ports in meta_info, and leave it RUNNING.  `ops stop`
        reaps it via the recorded pid (cli.main.ops_stop).

        Parity: the reference runs notebooks/TensorBoard as `V1Service`
        until stopped (SURVEY.md 2.4); locally the executor process is
        the operator-equivalent.
        """
        container = compiled.run.container
        argv = self._container_argv(container)
        env = self._build_env(run_uuid)
        _merge_container_env(env, container)
        ports = [int(p) for p in (compiled.run.ports or [])]
        if ports and _port_open("127.0.0.1", ports[0]):
            # A stale listener would make the readiness probe pass
            # while OUR process dies on EADDRINUSE — fail fast with
            # the real cause instead of a phantom-RUNNING record.
            raise ExecutionError(
                f"port {ports[0]} is already in use (a previous "
                f"service still in shutdown grace, or an unrelated "
                f"listener)")
        log_path = self.store.logs_path(run_uuid, "main")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        with open(log_path, "a") as sink:
            proc = subprocess.Popen(
                argv, env=env, cwd=container.working_dir,
                stdout=sink, stderr=subprocess.STDOUT,
                start_new_session=True)
        self.store.set_status(run_uuid, V1Statuses.RUNNING,
                              reason="LocalExecutor", force=True)
        self.store.update_run(run_uuid, meta_info={
            "service": {"pid": proc.pid, "ports": ports,
                        "host": "127.0.0.1"}})
        ready_timeout = float(os.environ.get(
            "POLYAXON_TPU_SERVICE_READY_TIMEOUT", "60"))
        deadline = time.time() + ready_timeout
        while True:
            # `ops stop` during startup reaps the pid and force-sets
            # "stopped" — honor it instead of misreading the kill as
            # a startup crash (FAILED) or respawning via retries.
            try:
                status = self.store.get_run(run_uuid).get("status")
            except Exception:
                status = None
            if status in (V1Statuses.STOPPING, V1Statuses.STOPPED):
                raise StopRequested()
            if proc.poll() is not None:
                raise ExecutionError(
                    f"service exited during startup "
                    f"(rc={proc.returncode}); see logs")
            if not ports or _port_open("127.0.0.1", ports[0]):
                # The port answering isn't proof OUR process owns it —
                # re-check liveness once so a racing listener can't
                # bless a dead service.
                if proc.poll() is not None:
                    raise ExecutionError(
                        f"service exited right after port "
                        f"{ports[0] if ports else '?'} opened "
                        f"(rc={proc.returncode}); see logs")
                return
            if time.time() >= deadline:
                try:
                    os.killpg(proc.pid, 15)
                except ProcessLookupError:
                    pass
                raise ExecutionError(
                    f"service did not answer on port {ports[0]} "
                    f"within {ready_timeout:.0f}s")
            time.sleep(0.25)

    def _run_job(self, run_uuid: str, compiled, timeout: Optional[float]) -> None:
        container = compiled.run.container
        argv = self._container_argv(container)
        env = self._build_env(run_uuid)
        _merge_container_env(env, container)
        self.store.set_status(run_uuid, V1Statuses.RUNNING,
                              reason="LocalExecutor", force=True)
        proc = self._spawn(run_uuid, argv, env, "main",
                           cwd=container.working_dir)
        self._wait(run_uuid, {"main": proc}, timeout)

    # -- distributed -----------------------------------------------------

    def _run_distributed(self, run_uuid: str, compiled,
                         timeout: Optional[float]) -> None:
        topo: ProcessTopology = normalize(compiled.run)
        port = _free_port()
        procs: Dict[str, subprocess.Popen] = {}
        self.store.set_status(run_uuid, V1Statuses.RUNNING,
                              reason="LocalExecutor", force=True)
        for group in topo.groups:
            container = group.spec.container or getattr(
                compiled.run, "worker", None) and compiled.run.worker.container
            argv = self._container_argv(container)
            for index in range(group.replicas):
                replica = f"{group.role}-{index}"
                topo_env = topo.process_env(group.role, index, run=run_uuid,
                                            port=port)
                # Local simulation: every process is on this host.
                topo_env["PTPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
                env = self._build_env(run_uuid, topo_env)
                _merge_container_env(env, container)
                procs[replica] = self._spawn(run_uuid, argv, env, replica,
                                             cwd=container.working_dir)
        self._wait(run_uuid, procs, timeout)

    # -- dag -------------------------------------------------------------

    def _run_dag(self, run_uuid: str, operation: V1Operation, compiled) -> None:
        from .dag import DagError, DagRunner, DagStopped

        self.store.set_status(run_uuid, V1Statuses.RUNNING,
                              reason="LocalExecutor", force=True)
        try:
            DagRunner(self, compiled, pipeline_uuid=run_uuid).execute()
        except DagStopped as e:
            raise StopRequested() from e
        except DagError as e:
            raise ExecutionError(str(e)) from e
