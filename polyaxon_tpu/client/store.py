"""File-based run store: the local persistence layer.

Layout (under ``$POLYAXON_TPU_HOME`` or ``~/.polyaxon_tpu``):

    runs/<uuid>/
        metadata.json       run record (name, project, spec, inputs/outputs, status)
        statuses.jsonl      append-only status conditions
        events/<kind>/<name>.jsonl   tracked event series (metrics, images, ...)
        logs/<replica>.log  run logs
        artifacts/          run workspace (outputs/ inside)
        lineage.jsonl       artifact lineage records

The control plane (SURVEY.md 2.8) wraps this same store behind an HTTP API;
local single-process mode uses it directly, which is what makes
``ptpu run`` work with zero services running.
"""

from __future__ import annotations

import fcntl
import json
import os
import re
import time
import uuid as uuidlib
from typing import Any, Dict, Iterator, List, Optional

from ..lifecycle import V1StatusCondition, V1Statuses, can_transition

# Identifiers that become path components (run uuids, replica names, event
# kinds).  The store is exposed over the network by the control-plane API,
# so a traversal segment here would be remote file write/delete.
_SAFE_ID = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


def check_safe_id(value: str, what: str = "run_uuid") -> str:
    if not isinstance(value, str) or not _SAFE_ID.match(value) \
            or value in (".", ".."):
        raise StoreError(f"Invalid {what}: {value!r}")
    return value


def default_home() -> str:
    return os.environ.get(
        "POLYAXON_TPU_HOME",
        os.path.join(os.path.expanduser("~"), ".polyaxon_tpu"),
    )


class StoreError(RuntimeError):
    pass


class _Locked:
    """fcntl-based advisory lock guarding metadata read-modify-write."""

    def __init__(self, path: str):
        self._path = path + ".lock"
        self._fh = None

    def __enter__(self):
        self._fh = open(self._path, "a+")
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
        self._fh.close()


class FileRunStore:
    """CRUD + append streams for run records on the local filesystem."""

    def __init__(self, home: Optional[str] = None):
        self.home = home or default_home()
        self.runs_root = os.path.join(self.home, "runs")
        os.makedirs(self.runs_root, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def run_path(self, run_uuid: str) -> str:
        return os.path.join(self.runs_root, check_safe_id(run_uuid))

    def artifacts_path(self, run_uuid: str) -> str:
        return os.path.join(self.run_path(run_uuid), "artifacts")

    def outputs_path(self, run_uuid: str) -> str:
        return os.path.join(self.artifacts_path(run_uuid), "outputs")

    def events_path(self, run_uuid: str, kind: str, name: str) -> str:
        safe = name.replace("/", "__").replace("\\", "__").replace("\0", "_")
        check_safe_id(kind, "event kind")
        if safe in (".", ".."):
            safe = safe + "_"
        return os.path.join(self.run_path(run_uuid), "events", kind,
                            f"{safe}.jsonl")

    def logs_path(self, run_uuid: str, replica: str = "main") -> str:
        check_safe_id(replica, "replica")
        return os.path.join(self.run_path(run_uuid), "logs", f"{replica}.log")

    def _meta_path(self, run_uuid: str) -> str:
        return os.path.join(self.run_path(run_uuid), "metadata.json")

    # -- run CRUD ---------------------------------------------------------

    def create_run(
        self,
        name: Optional[str] = None,
        project: str = "default",
        description: Optional[str] = None,
        tags: Optional[List[str]] = None,
        content: Optional[Dict[str, Any]] = None,
        kind: Optional[str] = None,
        pipeline: Optional[str] = None,
        meta_info: Optional[Dict[str, Any]] = None,
        run_uuid: Optional[str] = None,
        managed_by: str = "local",
        queue: Optional[str] = None,
        priority: int = 0,
    ) -> Dict[str, Any]:
        run_uuid = run_uuid or uuidlib.uuid4().hex[:12]
        path = self.run_path(run_uuid)
        if os.path.exists(path):
            raise StoreError(f"Run {run_uuid} already exists")
        for sub in ("events", "logs", "artifacts/outputs"):
            os.makedirs(os.path.join(path, sub), exist_ok=True)
        record = {
            "uuid": run_uuid,
            "name": name or run_uuid,
            "project": project,
            "description": description,
            "tags": tags or [],
            "kind": kind,
            "content": content,
            "pipeline": pipeline,
            "meta_info": meta_info or {},
            "managed_by": managed_by,
            "queue": queue,
            "priority": int(priority or 0),
            "status": V1Statuses.CREATED,
            "created_at": time.time(),
            "updated_at": time.time(),
            "started_at": None,
            "finished_at": None,
            "wait_time": None,
            "duration": None,
            "inputs": {},
            "outputs": {},
        }
        self._write_meta(run_uuid, record)
        self._append_status_line(run_uuid, V1StatusCondition(
            type=V1Statuses.CREATED, reason="StoreCreate"))
        return record

    def _write_meta(self, run_uuid: str, record: Dict[str, Any]) -> None:
        path = self._meta_path(run_uuid)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
        os.replace(tmp, path)

    def get_run(self, run_uuid: str) -> Dict[str, Any]:
        path = self._meta_path(run_uuid)
        if not os.path.exists(path):
            raise StoreError(f"Run {run_uuid} not found")
        with open(path) as f:
            return json.load(f)

    def update_run(self, run_uuid: str, **fields: Any) -> Dict[str, Any]:
        with _Locked(self._meta_path(run_uuid)):
            record = self.get_run(run_uuid)
            for key, value in fields.items():
                if key in ("inputs", "outputs", "meta_info") and \
                        isinstance(value, dict):
                    record.setdefault(key, {}).update(value)
                else:
                    record[key] = value
            record["updated_at"] = time.time()
            self._write_meta(run_uuid, record)
        return record

    def delete_run(self, run_uuid: str) -> None:
        import shutil

        path = self.run_path(run_uuid)
        if os.path.exists(path):
            shutil.rmtree(path)

    def list_runs(
        self,
        project: Optional[str] = None,
        pipeline: Optional[str] = None,
        query: Optional[str] = None,
        sort: Optional[str] = None,
        limit: Optional[int] = None,
        offset: int = 0,
    ) -> List[Dict[str, Any]]:
        from ..query import apply_query, apply_sort

        records = []
        for entry in sorted(os.listdir(self.runs_root)):
            meta = self._meta_path(entry)
            if not os.path.exists(meta):
                continue
            try:
                with open(meta) as f:
                    record = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue
            if project and record.get("project") != project:
                continue
            if pipeline and record.get("pipeline") != pipeline:
                continue
            records.append(record)
        if query:
            records = apply_query(records, query,
                                  metrics_reader=self.last_metrics)
            for r in records:
                r.pop("_metrics", None)  # internal query cache
        records = apply_sort(records, sort or "-created_at")
        if offset:
            records = records[offset:]
        if limit is not None:
            records = records[:limit]
        return records

    # -- statuses ---------------------------------------------------------

    def _statuses_path(self, run_uuid: str) -> str:
        return os.path.join(self.run_path(run_uuid), "statuses.jsonl")

    def _append_status_line(self, run_uuid: str,
                            condition: V1StatusCondition) -> None:
        with open(self._statuses_path(run_uuid), "a") as f:
            f.write(json.dumps(condition.to_dict()) + "\n")

    def set_status(
        self,
        run_uuid: str,
        status: str,
        reason: Optional[str] = None,
        message: Optional[str] = None,
        force: bool = False,
    ) -> bool:
        """Transition a run's status; returns False for illegal transitions."""
        with _Locked(self._meta_path(run_uuid)):
            record = self.get_run(run_uuid)
            current = record.get("status")
            if not force and not can_transition(current, status):
                return False
            now = time.time()
            record["status"] = status
            record["updated_at"] = now
            if status == V1Statuses.RUNNING and not record.get("started_at"):
                record["started_at"] = now
                record["wait_time"] = now - record["created_at"]
            if status in V1Statuses.DONE:
                record["finished_at"] = now
                if record.get("started_at"):
                    record["duration"] = now - record["started_at"]
            self._write_meta(run_uuid, record)
        self._append_status_line(
            run_uuid,
            V1StatusCondition(type=status, reason=reason, message=message),
        )
        return True

    def get_statuses(self, run_uuid: str) -> List[V1StatusCondition]:
        path = self._statuses_path(run_uuid)
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(V1StatusCondition.from_dict(json.loads(line)))
        return out

    # -- heartbeat (zombie detection, SURVEY.md 5.3) ---------------------

    def touch_heartbeat(self, run_uuid: str) -> None:
        """Record liveness: the tracking writer touches this while the
        training process is alive; the control plane's zombie sweep
        fails RUNNING runs whose heartbeat goes stale."""
        path = os.path.join(self.run_path(run_uuid), "heartbeat")
        try:
            os.utime(path)
        except OSError:
            with open(path, "w") as f:
                f.write("")

    def heartbeat_at(self, run_uuid: str) -> Optional[float]:
        """mtime of the last heartbeat, or None if the run never sent
        one (runs without tracking must never be declared zombies)."""
        try:
            return os.stat(
                os.path.join(self.run_path(run_uuid), "heartbeat")).st_mtime
        except OSError:
            return None

    # -- events (metrics & co) -------------------------------------------

    def append_events(self, run_uuid: str, kind: str, name: str,
                      events: List[Dict[str, Any]]) -> None:
        path = self.events_path(run_uuid, kind, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            for event in events:
                f.write(json.dumps(event, default=str) + "\n")

    def read_events(self, run_uuid: str, kind: str, name: str,
                    offset: int = 0) -> List[Dict[str, Any]]:
        path = self.events_path(run_uuid, kind, name)
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for i, line in enumerate(f):
                if i < offset:
                    continue
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out

    def list_events(self, run_uuid: str, kind: Optional[str] = None) -> Dict[str, List[str]]:
        root = os.path.join(self.run_path(run_uuid), "events")
        out: Dict[str, List[str]] = {}
        if not os.path.isdir(root):
            return out
        kinds = [check_safe_id(kind, "event kind")] if kind \
            else sorted(os.listdir(root))
        for k in kinds:
            kdir = os.path.join(root, k)
            if os.path.isdir(kdir):
                out[k] = sorted(
                    f[:-6] for f in os.listdir(kdir) if f.endswith(".jsonl")
                )
        return out

    def last_metrics(self, run_uuid: str) -> Dict[str, float]:
        """Final value of each tracked metric (used by tuner joins/queries)."""
        out: Dict[str, float] = {}
        for name in self.list_events(run_uuid, "metric").get("metric", []):
            events = self.read_events(run_uuid, "metric", name)
            if events:
                out[name] = events[-1].get("value")
        return out

    # -- logs -------------------------------------------------------------

    def append_log(self, run_uuid: str, text: str, replica: str = "main") -> None:
        path = self.logs_path(run_uuid, replica)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(text)

    def read_logs(self, run_uuid: str, replica: Optional[str] = None,
                  tail: Optional[int] = None) -> str:
        root = os.path.join(self.run_path(run_uuid), "logs")
        if not os.path.isdir(root):
            return ""
        if replica is not None:
            check_safe_id(replica, "replica")
        files = sorted(os.listdir(root)) if replica is None else [f"{replica}.log"]
        chunks = []
        for fname in files:
            path = os.path.join(root, fname)
            if os.path.exists(path):
                with open(path) as f:
                    text = f.read()
                if len(files) > 1:
                    chunks.append(f"==> {fname} <==\n{text}")
                else:
                    chunks.append(text)
        text = "\n".join(chunks)
        if tail is not None:
            text = "\n".join(text.splitlines()[-tail:])
        return text

    # -- lineage ----------------------------------------------------------

    def add_lineage(self, run_uuid: str, record: Dict[str, Any]) -> None:
        path = os.path.join(self.run_path(run_uuid), "lineage.jsonl")
        with open(path, "a") as f:
            f.write(json.dumps(record, default=str) + "\n")

    def get_lineage(self, run_uuid: str) -> List[Dict[str, Any]]:
        path = os.path.join(self.run_path(run_uuid), "lineage.jsonl")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [json.loads(l) for l in f if l.strip()]
