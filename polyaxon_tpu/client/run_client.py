"""RunClient / ProjectClient: the capability hub over a store backend.

Env-var wiring parity (SURVEY.md 2.9/3.2): inside a launched container the
agent injects ``POLYAXON_TPU_RUN_UUID``/``POLYAXON_TPU_PROJECT`` (and, for
distributed runs, the PTPU_* topology block), so ``RunClient()`` with no
args attaches to the active run — exactly how the reference's
``tracking.init()`` self-identifies.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ..lifecycle import V1Statuses
from .store import FileRunStore, StoreError

ENV_RUN_UUID = "POLYAXON_TPU_RUN_UUID"
ENV_PROJECT = "POLYAXON_TPU_PROJECT"
ENV_API_HOST = "POLYAXON_TPU_HOST"


def get_client(home: Optional[str] = None) -> "FileRunStore":
    """Backend selection: HTTP transport when an API host is configured and
    reachable, else the local file store."""
    host = os.environ.get(ENV_API_HOST)
    if host:
        from .api_client import ApiRunStore  # lazy; needs no extra deps

        return ApiRunStore(host)
    return FileRunStore(home)


class RunClient:
    """CRUD + streams for one run."""

    def __init__(
        self,
        run_uuid: Optional[str] = None,
        project: Optional[str] = None,
        store: Optional[FileRunStore] = None,
        home: Optional[str] = None,
    ):
        self.store = store or get_client(home)
        self.project = project or os.environ.get(ENV_PROJECT, "default")
        self.run_uuid = run_uuid or os.environ.get(ENV_RUN_UUID)
        self._run_data: Optional[Dict[str, Any]] = None

    # -- lifecycle --------------------------------------------------------

    def create(
        self,
        name: Optional[str] = None,
        description: Optional[str] = None,
        tags: Optional[List[str]] = None,
        content: Optional[Dict[str, Any]] = None,
        kind: Optional[str] = None,
        pipeline: Optional[str] = None,
        meta_info: Optional[Dict[str, Any]] = None,
        managed_by: str = "local",
        queue: Optional[str] = None,
        priority: int = 0,
    ) -> Dict[str, Any]:
        record = self.store.create_run(
            name=name, project=self.project, description=description,
            tags=tags, content=content, kind=kind, pipeline=pipeline,
            meta_info=meta_info, managed_by=managed_by,
            queue=queue, priority=priority,
        )
        self.run_uuid = record["uuid"]
        self._run_data = record
        return record

    def refresh_data(self) -> Dict[str, Any]:
        self._require_run()
        self._run_data = self.store.get_run(self.run_uuid)
        return self._run_data

    @property
    def run_data(self) -> Dict[str, Any]:
        if self._run_data is None:
            self.refresh_data()
        return self._run_data

    def update(self, **fields: Any) -> Dict[str, Any]:
        self._require_run()
        self._run_data = self.store.update_run(self.run_uuid, **fields)
        return self._run_data

    def _require_run(self) -> None:
        if not self.run_uuid:
            raise StoreError(
                "No run is attached: pass run_uuid or set "
                f"{ENV_RUN_UUID} (injected automatically inside managed runs)"
            )

    # -- statuses ---------------------------------------------------------

    def log_status(self, status: str, reason: Optional[str] = None,
                   message: Optional[str] = None, force: bool = False) -> bool:
        self._require_run()
        return self.store.set_status(self.run_uuid, status, reason=reason,
                                     message=message, force=force)

    def get_statuses(self):
        self._require_run()
        return self.store.get_statuses(self.run_uuid)

    def get_status(self) -> Optional[str]:
        return self.refresh_data().get("status")

    def log_succeeded(self, message: Optional[str] = None) -> None:
        self.log_status(V1Statuses.SUCCEEDED, reason="ClientDone",
                        message=message)

    def log_failed(self, reason: Optional[str] = None,
                   message: Optional[str] = None) -> None:
        self.log_status(V1Statuses.FAILED, reason=reason or "ClientFailed",
                        message=message)

    def log_stopped(self, message: Optional[str] = None) -> None:
        self.log_status(V1Statuses.STOPPED, reason="ClientStop",
                        message=message)

    # -- io / meta --------------------------------------------------------

    def log_inputs(self, **inputs: Any) -> None:
        self.update(inputs=inputs)

    def log_outputs(self, **outputs: Any) -> None:
        self.update(outputs=outputs)

    def log_meta(self, **meta: Any) -> None:
        self.update(meta_info=meta)

    def log_tags(self, tags: List[str]) -> None:
        current = set(self.run_data.get("tags") or [])
        self.update(tags=sorted(current | set(tags)))

    # -- events / metrics / logs -----------------------------------------

    def touch_heartbeat(self) -> None:
        self._require_run()
        self.store.touch_heartbeat(self.run_uuid)

    def append_events(self, kind: str, name: str,
                      events: List[Dict[str, Any]]) -> None:
        self._require_run()
        self.store.append_events(self.run_uuid, kind, name, events)

    def get_metrics(self, name: str) -> List[Dict[str, Any]]:
        self._require_run()
        return self.store.read_events(self.run_uuid, "metric", name)

    def get_last_metrics(self) -> Dict[str, float]:
        self._require_run()
        return self.store.last_metrics(self.run_uuid)

    def log_text(self, text: str, replica: str = "main") -> None:
        self._require_run()
        self.store.append_log(self.run_uuid, text, replica=replica)

    def get_logs(self, replica: Optional[str] = None,
                 tail: Optional[int] = None) -> str:
        self._require_run()
        return self.store.read_logs(self.run_uuid, replica=replica, tail=tail)

    # -- artifacts --------------------------------------------------------

    def get_artifacts_path(self) -> str:
        self._require_run()
        return self.store.artifacts_path(self.run_uuid)

    def get_outputs_path(self) -> str:
        self._require_run()
        return self.store.outputs_path(self.run_uuid)

    def log_artifact_lineage(self, name: str, kind: str, path: str,
                             summary: Optional[Dict[str, Any]] = None) -> None:
        self._require_run()
        self.store.add_lineage(self.run_uuid, {
            "name": name, "kind": kind, "path": path,
            "summary": summary or {},
        })

    def get_artifacts_lineage(self) -> List[Dict[str, Any]]:
        self._require_run()
        return self.store.get_lineage(self.run_uuid)


class ProjectClient:
    """List/search runs in a project."""

    def __init__(self, project: Optional[str] = None,
                 store: Optional[FileRunStore] = None,
                 home: Optional[str] = None):
        self.project = project or os.environ.get(ENV_PROJECT, "default")
        self.store = store or get_client(home)

    def list_runs(self, query: Optional[str] = None, sort: Optional[str] = None,
                  limit: Optional[int] = None, offset: int = 0):
        return self.store.list_runs(project=self.project, query=query,
                                    sort=sort, limit=limit, offset=offset)
