"""Clients: the hub every consumer (CLI, tracking, tuner, agent) goes through.

Parity: reference ``RunClient``/``ProjectClient`` (SURVEY.md 2.7).  Local
mode talks straight to the file store; API mode (control plane) swaps in an
HTTP transport with the same interface.
"""

from .run_client import ProjectClient, RunClient, get_client
from .store import FileRunStore, StoreError, default_home
