"""HTTP store backend: same interface as FileRunStore over the control
plane's REST API (SURVEY.md 2.7/2.8).

Implemented with stdlib urllib only.  The server half lives in
``polyaxon_tpu.scheduler.api``; until a host is actually serving,
construction fails fast with a clear message instead of an import error.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from ..lifecycle import V1StatusCondition
from .store import StoreError


class ApiRunStore:
    """FileRunStore-compatible facade speaking to the control plane."""

    def __init__(self, host: str, timeout: float = 30.0,
                 token: Optional[str] = None):
        self.host = host.rstrip("/")
        if not self.host.startswith(("http://", "https://")):
            self.host = "http://" + self.host
        self.timeout = timeout
        if token is None:
            from ..config import ClientConfig

            token = ClientConfig.load().token  # env-over-file layering
        self.token = token

    # -- transport --------------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, Any]] = None) -> Any:
        url = f"{self.host}/api/v1{path}"
        if params:
            qs = urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
            if qs:
                url += "?" + qs
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise StoreError(
                f"API {method} {path} failed: {e.code} {detail}") from e
        except urllib.error.URLError as e:
            raise StoreError(
                f"Control plane at {self.host} unreachable: {e.reason}") from e
        return json.loads(payload) if payload else None

    # -- FileRunStore interface -------------------------------------------

    def create_run(self, **kwargs: Any) -> Dict[str, Any]:
        return self._request("POST", "/runs", body=kwargs)

    def get_run(self, run_uuid: str) -> Dict[str, Any]:
        return self._request("GET", f"/runs/{run_uuid}")

    def update_run(self, run_uuid: str, **fields: Any) -> Dict[str, Any]:
        return self._request("PATCH", f"/runs/{run_uuid}", body=fields)

    def delete_run(self, run_uuid: str) -> None:
        self._request("DELETE", f"/runs/{run_uuid}")

    def list_runs(self, project: Optional[str] = None,
                  pipeline: Optional[str] = None,
                  query: Optional[str] = None, sort: Optional[str] = None,
                  limit: Optional[int] = None,
                  offset: int = 0) -> List[Dict[str, Any]]:
        return self._request("GET", "/runs", params={
            "project": project, "pipeline": pipeline, "query": query,
            "sort": sort, "limit": limit, "offset": offset or None,
        })

    def set_status(self, run_uuid: str, status: str,
                   reason: Optional[str] = None, message: Optional[str] = None,
                   force: bool = False) -> bool:
        out = self._request("POST", f"/runs/{run_uuid}/statuses", body={
            "status": status, "reason": reason, "message": message,
            "force": force,
        })
        return bool(out and out.get("ok"))

    def get_statuses(self, run_uuid: str) -> List[V1StatusCondition]:
        out = self._request("GET", f"/runs/{run_uuid}/statuses") or []
        return [V1StatusCondition.from_dict(c) for c in out]

    def append_events(self, run_uuid: str, kind: str, name: str,
                      events: List[Dict[str, Any]]) -> None:
        self._request("POST", f"/runs/{run_uuid}/events", body={
            "kind": kind, "name": name, "events": events,
        })

    def touch_heartbeat(self, run_uuid: str) -> None:
        self._request("POST", f"/runs/{run_uuid}/heartbeat")

    def heartbeat_at(self, run_uuid: str) -> Optional[float]:
        out = self._request("GET", f"/runs/{run_uuid}/heartbeat") or {}
        return out.get("heartbeat_at")

    def read_events(self, run_uuid: str, kind: str, name: str,
                    offset: int = 0) -> List[Dict[str, Any]]:
        return self._request("GET", f"/runs/{run_uuid}/events", params={
            "kind": kind, "name": name, "offset": offset or None,
        }) or []

    def list_events(self, run_uuid: str,
                    kind: Optional[str] = None) -> Dict[str, List[str]]:
        return self._request("GET", f"/runs/{run_uuid}/events/names",
                             params={"kind": kind}) or {}

    def last_metrics(self, run_uuid: str) -> Dict[str, float]:
        return self._request("GET", f"/runs/{run_uuid}/metrics/last") or {}

    def append_log(self, run_uuid: str, text: str,
                   replica: str = "main") -> None:
        self._request("POST", f"/runs/{run_uuid}/logs", body={
            "text": text, "replica": replica,
        })

    def read_logs(self, run_uuid: str, replica: Optional[str] = None,
                  tail: Optional[int] = None) -> str:
        out = self._request("GET", f"/runs/{run_uuid}/logs", params={
            "replica": replica, "tail": tail,
        })
        return out.get("logs", "") if isinstance(out, dict) else (out or "")

    def claim(self, agent: str,
              queues: Optional[List[str]] = None) -> Optional[Dict[str, Any]]:
        """Agent-side: claim the next queued run (None when queue empty)."""
        out = self._request("POST", "/agent/claim",
                            body={"agent": agent, "queues": queues})
        return out or None

    def read_logs_from(self, run_uuid: str, replica: Optional[str],
                       offset: int) -> Dict[str, Any]:
        """Incremental log read for streaming (offset in, new text out)."""
        return self._request("GET", f"/runs/{run_uuid}/logs", params={
            "replica": replica, "offset": offset,
        }) or {"logs": "", "offset": offset}

    def read_logs_multi(self, run_uuid: str,
                        offsets: Dict[str, int]) -> Dict[str, Any]:
        """Per-replica incremental reads (the `ops logs --follow` path)."""
        return self._request("GET", f"/runs/{run_uuid}/logs", params={
            "offsets": json.dumps(offsets),
        }) or {"replicas": {}}

    def add_lineage(self, run_uuid: str, record: Dict[str, Any]) -> None:
        self._request("POST", f"/runs/{run_uuid}/lineage", body=record)

    def get_lineage(self, run_uuid: str) -> List[Dict[str, Any]]:
        return self._request("GET", f"/runs/{run_uuid}/lineage") or []

    # Local-path helpers: API mode still materializes artifacts/logs
    # locally under the home tree (the sidecar/agent relay them to the
    # control plane); reuse the file layout.

    @property
    def home(self) -> str:
        from .store import default_home

        return default_home()

    def logs_path(self, run_uuid: str, replica: str = "main") -> str:
        import os

        return os.path.join(self.home, "runs", run_uuid, "logs",
                            f"{replica}.log")

    def artifacts_path(self, run_uuid: str) -> str:
        from ..compiler.contexts import run_artifacts_path

        import os

        path = run_artifacts_path(run_uuid)
        os.makedirs(os.path.join(path, "outputs"), exist_ok=True)
        return path

    def outputs_path(self, run_uuid: str) -> str:
        import os

        return os.path.join(self.artifacts_path(run_uuid), "outputs")
