"""Input pipeline: shuffled epoch iteration + host->device prefetch.

VERDICT r1 #4: the reference delegates data loading to user code, but a
framework that owns the training loop owns the input path too.  This
module provides:

- ``ArrayDataset``: in-memory (or memmapped) arrays -> shuffled epoch
  batches, deterministic per (seed, epoch).
- ``npy_dataset``: ``inputs.npy``/``labels.npy`` from a directory,
  loaded with ``mmap_mode="r"`` so datasets larger than RAM stream.
- ``synthetic_dataset``: a deterministic pool (default 64 batches) of
  synthetic data cycled with reshuffling — training sees varied batches
  while staying reproducible, unlike round 1's single static batch.
- ``digits_dataset``: a real, offline-available classification set
  (scikit-learn's 8x8 handwritten digits) with a held-out eval split —
  the BASELINE config-1 stand-in, since MNIST itself cannot be
  downloaded in a zero-egress environment.
- ``prefetch_to_device``: a background thread that stages the next
  batches onto the devices (with the step's batch sharding) so the host
  copy overlaps device compute.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np


def _epoch_rng(seed: int, epoch: int) -> np.random.RandomState:
    """One seed-mixing formula for every dataset (deterministic per
    (seed, epoch), distinct across epochs)."""
    return np.random.RandomState((seed * 100003 + epoch) % (2 ** 31))


class _EpochIterable:
    """Shared epoch chaining: subclasses define ``epoch(e, start=0)``.

    Every dataset is deterministic in (seed, epoch), which makes the
    stream CHECKPOINTABLE by position alone: ``epochs(start_step=k)``
    resumes exactly where an uninterrupted run's k-th batch would be —
    no iterator state to serialize.  train.py passes the restored step
    so a preemption-resumed run continues through the data instead of
    replaying batch 0 (exactly-once over the schedule).
    """

    def __iter__(self):
        return self.epoch(0)

    def epochs(self, n: Optional[int] = None, *, start_step: int = 0
               ) -> Iterator[Dict[str, np.ndarray]]:
        spe = self.steps_per_epoch
        e, skip = divmod(int(start_step), spe) if start_step else (0, 0)
        while n is None or e < n:
            yield from self.epoch(e, start=skip)
            skip = 0
            e += 1


class ArrayDataset(_EpochIterable):
    """Dict-of-arrays -> iterator of shuffled, fixed-size batches.

    Iterating yields one epoch.  ``epochs(n)`` chains n epochs (n=None
    for an endless stream), reshuffling every epoch deterministically
    from (seed, epoch).
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 *, shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) > 1:
            raise ValueError(f"Array length mismatch: {sizes}")
        self.arrays = arrays
        self.n = next(iter(sizes.values())) if sizes else 0
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        if self.n < self.batch_size:
            raise ValueError(
                f"Dataset of {self.n} examples can't fill a batch of "
                f"{self.batch_size}")

    @property
    def steps_per_epoch(self) -> int:
        return self.n // self.batch_size if self.drop_remainder \
            else -(-self.n // self.batch_size)

    def sample(self, n: int = 2) -> Dict[str, np.ndarray]:
        """A shape-defining sample (model init / sharding layout)."""
        return {k: np.asarray(v[:n]) for k, v in self.arrays.items()}

    def epoch(self, epoch: int = 0, start: int = 0
              ) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(self.n)
        if self.shuffle:
            _epoch_rng(self.seed, epoch).shuffle(order)
        stop = self.n - (self.n % self.batch_size) \
            if self.drop_remainder else self.n
        # ``start`` skips whole batches without gathering them (resume
        # through memmapped arrays costs nothing).
        for lo in range(start * self.batch_size, stop, self.batch_size):
            idx = order[lo:lo + self.batch_size]
            idx.sort()  # monotone gather: fast on memmapped arrays
            yield {k: np.asarray(v[idx]) for k, v in self.arrays.items()}


def npy_dataset(data_dir: str, batch_size: int, *, shuffle: bool = True,
                seed: int = 0) -> ArrayDataset:
    arrays = {"inputs": np.load(os.path.join(data_dir, "inputs.npy"),
                                mmap_mode="r")}
    labels_path = os.path.join(data_dir, "labels.npy")
    if os.path.exists(labels_path):
        arrays["labels"] = np.load(labels_path, mmap_mode="r")
    return ArrayDataset(arrays, batch_size, shuffle=shuffle, seed=seed)


def synthetic_dataset(spec, batch_size: int, *, pool_batches: int = 64,
                      pool_budget_bytes: int = 256 * 1024 * 1024,
                      seed: int = 0) -> ArrayDataset:
    """Deterministic varied data from a model spec's batch generator.

    The pool is capped by ``pool_budget_bytes`` so large-input models
    (resnet50 at batch 128 is ~77 MB/batch) don't materialize gigabytes
    of host RAM just to provide shuffle variety.
    """
    probe = spec.make_batch(batch_size)
    batch_bytes = sum(np.asarray(v).nbytes for v in probe.values())
    pool_batches = max(2, min(pool_batches,
                              pool_budget_bytes // max(batch_bytes, 1)))
    pool = spec.make_batch(batch_size * pool_batches)
    return ArrayDataset({k: np.asarray(v) for k, v in pool.items()},
                        batch_size, shuffle=True, seed=seed)


def digits_dataset(batch_size: int, *, split: str = "train",
                   eval_fraction: float = 0.2, seed: int = 0
                   ) -> ArrayDataset:
    """Real 10-class image data available offline (sklearn digits).

    1797 8x8 grayscale digit images; deterministic train/eval split.
    The eval split keeps its remainder batch — truncating the held-out
    set would bias the reported accuracy.
    """
    try:
        from sklearn.datasets import load_digits
    except ImportError as e:
        raise RuntimeError(
            "--dataset digits needs scikit-learn (install the "
            "'polyaxon-tpu[data]' extra); use --dataset synthetic or "
            "--data-dir with .npy arrays instead") from e

    d = load_digits()
    images = (d.images / 16.0).astype("float32")[..., None]  # [N,8,8,1]
    labels = d.target.astype("int32")
    order = np.arange(len(images))
    np.random.RandomState(seed).shuffle(order)
    n_eval = int(len(images) * eval_fraction)
    idx = order[n_eval:] if split == "train" else order[:n_eval]
    train = split == "train"
    return ArrayDataset({"inputs": images[idx], "labels": labels[idx]},
                        min(batch_size, len(idx)),
                        shuffle=train, drop_remainder=train, seed=seed)


class TokenWindowDataset(_EpochIterable):
    """Contiguous token stream -> random fixed-length training windows.

    The standard LM data layout (one long token array on disk, sampled
    at random offsets): ``tokens`` is a 1-D integer array (memmap
    welcome — sampling reads only the touched windows).  Each epoch
    yields ``len(tokens) // (batch * seq_len)`` batches of
    ``{"inputs": [batch, seq_len]}``, offsets drawn deterministically
    from (seed, epoch); the registry's LM losses shift inputs
    internally, so no separate labels array exists.
    """

    def __init__(self, tokens: np.ndarray, batch_size: int,
                 seq_len: int, *, seed: int = 0):
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D; got {tokens.shape}")
        if len(tokens) < seq_len + 1:
            raise ValueError(
                f"{len(tokens)} tokens can't fill a window of {seq_len}")
        self.tokens = tokens
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.seed = seed

    @property
    def steps_per_epoch(self) -> int:
        return max(1, len(self.tokens) //
                   (self.batch_size * self.seq_len))

    def sample(self, n: int = 2) -> Dict[str, np.ndarray]:
        # Clamp offsets: a stream longer than one window but shorter
        # than n non-overlapping windows still yields full-length rows.
        hi = len(self.tokens) - self.seq_len
        win = np.stack([self.tokens[o:o + self.seq_len]
                        for o in (min(i * self.seq_len, hi)
                                  for i in range(n))])
        return {"inputs": win.astype(np.int32)}

    def epoch(self, epoch: int = 0, start: int = 0
              ) -> Iterator[Dict[str, np.ndarray]]:
        rs = _epoch_rng(self.seed, epoch)
        hi = len(self.tokens) - self.seq_len
        for i in range(self.steps_per_epoch):
            offs = np.sort(rs.randint(0, hi + 1, size=self.batch_size))
            if i < start:
                continue  # rng consumed, window gather skipped
            batch = np.stack([self.tokens[o:o + self.seq_len]
                              for o in offs])
            yield {"inputs": batch.astype(np.int32)}


def _random_segmentation(total: int, parts: int,
                         rs: np.random.RandomState) -> np.ndarray:
    """Random composition of ``total`` into ``parts`` positive parts
    (uniform over compositions): choose parts-1 distinct cut points."""
    if parts <= 1:
        return np.array([total])
    cuts = np.sort(rs.choice(total - 1, size=parts - 1,
                             replace=False)) + 1
    return np.diff(np.concatenate([[0], cuts, [total]]))


class SpanCorruptionDataset(_EpochIterable):
    """T5's span-corruption pretraining objective over a token stream.

    Per example: a window of ``window_length`` tokens is split into
    alternating keep/noise segments (noise fraction ``noise_density``,
    mean noise-span length ``mean_span``); each noise span is replaced
    by one descending sentinel (vocab_size-1, vocab_size-2, ...) in
    the encoder input, and the decoder target is the concatenation of
    ``sentinel_i + span_i`` pairs followed by ``eos_id``.  Both sides
    are padded to the STATIC (``inputs_length``, ``targets_length``) —
    TPU programs want fixed shapes — with ``enc_mask``/``target_mask``
    marking real tokens (the registry's seq2seq loss applies them).
    The produced lengths are deterministic in ``window_length``, so a
    window that would overflow the static lengths (silently dropping
    noise spans) is rejected at construction; the default window is
    auto-sized to exactly fill ``inputs_length``.

    The stream's token ids must stay below
    ``vocab_size - num_sentinels`` (T5 reserves the top of the vocab
    for sentinels); ids at or above that range would collide and are
    rejected per batch.
    """

    def __init__(self, tokens: np.ndarray, batch_size: int,
                 inputs_length: int, targets_length: int, *,
                 vocab_size: int, window_length: Optional[int] = None,
                 noise_density: float = 0.15, mean_span: float = 3.0,
                 num_sentinels: int = 100, pad_id: int = 0,
                 eos_id: int = 1, seed: int = 0):
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D; got {tokens.shape}")
        if not 0.0 < noise_density < 1.0:
            raise ValueError(
                f"noise_density must be in (0, 1); got {noise_density}")
        self.noise_density = float(noise_density)
        self.mean_span = float(mean_span)
        self.num_sentinels = int(num_sentinels)
        if window_length is None:
            # Window sized so the corrupted input ((1-r)*W + spans)
            # fills inputs_length; spans ~= W*r/mean_span sentinels
            # are added.  Rounding can overshoot by a token or two —
            # shrink until the EXACT planned lengths fit (n_noise and
            # n_spans are deterministic in W, so this is checkable).
            window_length = min(
                len(tokens) - 1,
                round(inputs_length / (1.0 - noise_density
                                       + noise_density / mean_span)))
            while window_length > 1:
                need_in, need_tgt = self._plan(window_length)
                if need_in <= inputs_length and \
                        need_tgt <= targets_length:
                    break
                window_length -= 1
        else:
            need_in, need_tgt = self._plan(int(window_length))
            if need_in > inputs_length or need_tgt > targets_length:
                # Silent truncation would drop noise spans from the
                # target — a corrupted objective, not a shorter one.
                raise ValueError(
                    f"window_length {window_length} produces inputs of "
                    f"{need_in} and targets of {need_tgt}, exceeding "
                    f"the static (inputs_length={inputs_length}, "
                    f"targets_length={targets_length})")
        self.window_length = int(window_length)
        if len(tokens) < self.window_length + 1:
            raise ValueError(
                f"{len(tokens)} tokens can't fill a window of "
                f"{self.window_length}")
        self.tokens = tokens
        self.batch_size = int(batch_size)
        self.inputs_length = int(inputs_length)
        self.targets_length = int(targets_length)
        self.vocab_size = int(vocab_size)
        self.pad_id = int(pad_id)
        self.eos_id = int(eos_id)
        self.seed = seed

    def _counts(self, L: int):
        """(n_noise, n_spans) for a window of L — deterministic, so
        the produced lengths are exact, not worst-case."""
        n_noise = max(1, int(round(L * self.noise_density)))
        n_noise = min(n_noise, L - 1)
        n_spans = max(1, int(round(n_noise / self.mean_span)))
        n_spans = min(n_spans, n_noise, self.num_sentinels,
                      L - n_noise)
        return n_noise, n_spans

    def _plan(self, L: int):
        """Exact (input_len, target_len) a window of L produces."""
        n_noise, n_spans = self._counts(L)
        return L - n_noise + n_spans, n_noise + n_spans + 1

    @property
    def steps_per_epoch(self) -> int:
        return max(1, len(self.tokens) //
                   (self.batch_size * self.window_length))

    def _corrupt(self, window: np.ndarray, rs: np.random.RandomState):
        L = len(window)
        n_noise, n_spans = self._counts(L)
        noise_lens = _random_segmentation(n_noise, n_spans, rs)
        keep_lens = _random_segmentation(L - n_noise, n_spans, rs)
        sentinel0 = self.vocab_size - 1
        inp, tgt, pos = [], [], 0
        for i in range(n_spans):
            inp.extend(window[pos:pos + keep_lens[i]])
            pos += keep_lens[i]
            inp.append(sentinel0 - i)
            tgt.append(sentinel0 - i)
            tgt.extend(window[pos:pos + noise_lens[i]])
            pos += noise_lens[i]
        tgt.append(self.eos_id)
        return np.asarray(inp, np.int32), np.asarray(tgt, np.int32)

    def _pad(self, row: np.ndarray, length: int):
        row = row[:length]
        mask = np.zeros(length, np.int32)
        mask[:len(row)] = 1
        out = np.full(length, self.pad_id, np.int32)
        out[:len(row)] = row
        return out, mask

    def sample(self, n: int = 2) -> Dict[str, np.ndarray]:
        """First-batch rows (deterministic), sized to n — the trainer's
        compile-shape probe (TokenWindowDataset.sample contract)."""
        batch = next(self.epoch(0))
        reps = -(-n // self.batch_size)
        return {k: np.concatenate([v] * reps)[:n]
                for k, v in batch.items()}

    def epoch(self, epoch: int = 0, start: int = 0
              ) -> Iterator[Dict[str, np.ndarray]]:
        rs = _epoch_rng(self.seed, epoch)
        hi = len(self.tokens) - self.window_length
        limit = self.vocab_size - self.num_sentinels
        for step_i in range(self.steps_per_epoch):
            # ONE code path for skipped and emitted batches: the rng
            # consumption (offset draw + data-dependent segmentation
            # draws inside _corrupt) is identical by construction, so
            # a resume skip can never desynchronize the stream even if
            # the draw pattern changes later.  Skipped batches only
            # save the pad/stack/yield tail — numpy-only cost.
            emit = step_i >= start
            offs = np.sort(rs.randint(0, hi + 1,
                                      size=self.batch_size))
            ins, tgts, in_m, tgt_m = [], [], [], []
            for o in offs:
                window = np.asarray(
                    self.tokens[o:o + self.window_length], np.int64)
                if window.max() >= limit:
                    raise ValueError(
                        f"token id {int(window.max())} collides with "
                        f"the sentinel range [{limit}, "
                        f"{self.vocab_size}); re-pack the stream or "
                        f"lower num_sentinels")
                i, t = self._corrupt(window, rs)
                if not emit:
                    continue
                i, im = self._pad(i, self.inputs_length)
                t, tm = self._pad(t, self.targets_length)
                ins.append(i); tgts.append(t)
                in_m.append(im); tgt_m.append(tm)
            if emit:
                yield {"inputs": np.stack(ins),
                       "labels": np.stack(tgts),
                       "enc_mask": np.stack(in_m),
                       "target_mask": np.stack(tgt_m)}


def token_dataset(path: str, batch_size: int, seq_len: int, *,
                  seed: int = 0) -> TokenWindowDataset:
    """Load a token stream: ``tokens.npy`` (any int dtype) or a raw
    ``tokens.bin`` of uint16 (the common GPT-2-vocab packing).  ``path``
    may be the file or a directory containing it."""
    if os.path.isdir(path):
        for name in ("tokens.npy", "tokens.bin"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no tokens.npy/tokens.bin under {path}")
    if path.endswith(".npy"):
        tokens = np.load(path, mmap_mode="r")
    else:
        tokens = np.memmap(path, dtype=np.uint16, mode="r")
    return TokenWindowDataset(tokens, batch_size, seq_len, seed=seed)


def prefetch_to_device(batches: Iterator[Dict[str, np.ndarray]],
                       sharding=None, *, depth: int = 2
                       ) -> Iterator[Dict[str, Any]]:
    """Stage upcoming batches onto devices from a background thread.

    The host->device copy of batch t+1 overlaps the device compute of
    batch t; ``depth`` bounds staged HBM.  With sharding=None batches
    pass through un-transferred (jit will place them).

    CPU backend: the worker passes batches through UN-TRANSFERRED
    whatever ``sharding`` says.  There is no HBM to stage into — a
    host->"device" copy on CPU is the same RAM, so the "overlap" buys
    nothing — while a second thread's ``device_put`` racing
    main-thread compilation/execution is exactly the kind of
    concurrent client use some jaxlib CPU builds handle poorly.  jit
    places the host arrays exactly as it would have placed the
    staged ones, so tokens/metrics are unchanged.
    """
    import jax

    if sharding is not None and jax.default_backend() == "cpu":
        sharding = None

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()

    def worker():
        try:
            for batch in batches:
                if sharding is not None:
                    batch = jax.device_put(batch, sharding)
                q.put(batch)
        except Exception as e:  # surface in the consumer, not the thread
            q.put(e)
        finally:
            q.put(_END)

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, Exception):
            raise item
        yield item
