"""LOCK-ORDER: interprocedural lock-acquisition-graph analysis.

The per-module rule families (`analysis/rules/`) see one function at
a time; lock-order inversions live *between* functions — thread A
takes `_prefix_lock` then calls into something that takes
`_page_lock`, thread B does the reverse, and nothing in either
function alone looks wrong.  This module builds a whole-program model
of `serving/` (plus `analysis/locksan.py`, whose registry below names
the sanitized locks) and derives the static lock-acquisition graph:

1. **Program model** (`ProgramModel`): every function/method in the
   checked file set, with its class context; every lock *declaration*
   (``self.x = threading.Lock()/RLock()/Condition()``, ``FairLock()``,
   ``sanitizer.wrap("name", ...)``); attribute types inferred from
   ``self.x = ClassName(...)`` assignments; and, per function, the
   lexical walk results — lock acquisitions (``with`` items and
   ``.acquire()/.release()`` pairs, including try-lock forms), call
   sites, attribute writes, and thread spawns — each tagged with the
   set of locks lexically held at that point.

2. **Lock identity**: a lock is named by its declaring class —
   ``Telemetry._lock`` and ``Replica._lock`` are different locks even
   though both attributes are spelled ``_lock``.  The
   :data:`~polyaxon_tpu.analysis.locksan.LOCK_REGISTRY` in locksan.py
   canonicalizes aliases (the engine's ``device_lock`` *is* the
   server's ``_lock``) and pins static names to the runtime
   sanitizer's names so the static graph and ``LockSanitizer.stats()``
   edges speak the same vocabulary — that equality is what makes the
   static ⊇ runtime cross-check (tests/test_serving_smoke.py) a real
   test rather than a name-translation exercise.

3. **Edges**: ``a -> b`` when some thread can block acquiring ``b``
   while holding ``a`` — either lexically (nested ``with``) or
   through a call chain (may-analysis: the transitive acquisition set
   of every callee, propagated to fixpoint).  Every edge carries a
   witness: the function chain and line numbers from the frame that
   holds ``a`` down to the frame that acquires ``b``.

4. **Cycles**: a cycle over *blocking* edges is a potential deadlock
   and becomes a LOCK-ORDER finding whose message prints the full
   witness path for each edge.  Try-lock acquisitions
   (``acquire(False)`` / ``acquire(blocking=False)`` / finite
   ``timeout=``) still produce edges — the runtime sanitizer records
   them, so the cross-check needs them — but never *complete* a
   cycle, because a try-lock never waits.

The acyclic graph is committed as ``analysis/lockorder.json`` (the
canonical lock-order DAG); tests/test_analysis.py regenerates it and
fails on drift, so a PR that adds an ordering edge must ship the
artifact diff for review.

Known precision limits (deliberate, documented): receivers are
resolved through ``self`` attributes, single-declaring-class lookup,
and the RECEIVER_TYPES hints in locksan.py — a receiver the model
cannot type contributes no call edge; two instances of the same class
share one lock node (cross-instance hand-off looks like
self-deadlock, none exists in serving/ today); branches are explored
with copies of the held set, except ``try`` bodies and ``finally``
blocks, whose acquire/release effects flow through (the
acquire-in-try / release-in-finally idiom).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules._base import Finding, dotted_name, _src_line, _LOCK_NAME
from .locksan import LOCK_REGISTRY, RECEIVER_TYPES

__all__ = ["ProgramModel", "LockGraph", "build_model", "build_lock_graph",
           "lock_order_findings", "canonical_graph", "PROGRAM_SCOPE",
           "in_program_scope"]

# Files the whole-program analyses read.  Fixture tests feed virtual
# paths through the same predicate, so `/serving/` matching stays
# prefix-free.
PROGRAM_SCOPE = ("/serving/", "/analysis/locksan.py")


def in_program_scope(relpath: str) -> bool:
    p = "/" + relpath.replace("\\", "/")
    return any(s in p for s in PROGRAM_SCOPE)


_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "FairLock"}

# Mutating method calls that count as writes to the receiver attr for
# the THREAD-SHARE analysis (threads.py rides this model).
_MUTATORS = {"append", "appendleft", "add", "update", "clear", "extend",
             "remove", "discard", "insert", "pop", "popleft", "popitem",
             "setdefault", "sort", "reverse"}

_CTOR_NAMES = {"__init__", "__post_init__", "__new__"}

# Method names that collide with builtin-collection / stdlib APIs.
# The *unknown-receiver* call fallback (link iff exactly one program
# class defines the method) must not fire for these: `children.get()`
# on a dict would otherwise resolve to whatever program class happens
# to define `get`.  Typed receivers are unaffected — if the model
# knows the receiver's class, its `get` resolves normally.
_GENERIC_METHODS = frozenset({
    "get", "pop", "popitem", "setdefault", "update", "keys", "values",
    "items", "clear", "copy", "append", "appendleft", "extend",
    "insert", "remove", "sort", "reverse", "index", "count", "add",
    "discard", "popleft", "split", "rsplit", "join", "strip",
    "lstrip", "rstrip", "startswith", "endswith", "encode", "decode",
    "format", "read", "readline", "readinto", "write", "flush",
    "seek", "tell", "send", "recv", "put", "get_nowait", "put_nowait",
    "acquire", "release", "wait", "notify", "notify_all", "set",
    "is_set", "submit", "result", "close", "start", "run",
})


@dataclasses.dataclass
class LockDecl:
    cls: str                      # declaring class
    attr: str                     # attribute name
    relpath: str
    line: int
    wrap_name: Optional[str] = None   # sanitizer.wrap("<name>", ...) alias

    @property
    def static_id(self) -> str:
        return f"{self.cls}.{self.attr}"


@dataclasses.dataclass
class Acq:
    """One direct lock acquisition site."""
    canon: str
    line: int
    blocking: bool
    held: Tuple[str, ...]         # locks lexically held at this point


@dataclasses.dataclass
class CallSite:
    line: int
    held: Tuple[str, ...]
    targets: Tuple[str, ...]      # resolved callee fqns (may be empty)


@dataclasses.dataclass
class WriteSite:
    cls: str                      # owning class of the written attr
    attr: str
    line: int
    held: Tuple[str, ...]
    func: str                     # enclosing def chain (for findings)
    relpath: str


@dataclasses.dataclass
class ThreadSpawn:
    """A ``Thread(target=...)`` / ``Timer(t, fn)`` site."""
    line: int
    target_fqn: Optional[str]
    thread_name: Optional[str]
    relpath: str
    func: str


@dataclasses.dataclass
class FuncInfo:
    fqn: str                      # "relpath::Qual.chain"
    qual: str                     # def chain within the module
    name: str
    cls: Optional[str]            # innermost enclosing class, if any
    relpath: str
    node: ast.AST
    acquisitions: List[Acq] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    writes: List[WriteSite] = dataclasses.field(default_factory=list)
    spawns: List[ThreadSpawn] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    name: str
    relpath: str
    bases: Tuple[str, ...]        # base-class tail names
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)  # name -> fqn


class ProgramModel:
    """Parsed whole-program facts shared by LOCK-ORDER and
    THREAD-SHARE.  Build with :func:`build_model`."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.lock_decls: Dict[Tuple[str, str], LockDecl] = {}
        self.lock_attr_classes: Dict[str, List[str]] = {}
        self.attr_types: Dict[Tuple[str, str], str] = {}
        self.module_funcs: Dict[Tuple[str, str], List[str]] = {}
        self.sources: Dict[str, Sequence[str]] = {}
        self.unresolved_calls: int = 0

    # -- identity -----------------------------------------------------

    def canon_lock(self, cls: Optional[str], attr: str,
                   wrap_name: Optional[str] = None) -> str:
        """Canonical graph-node name for a lock attribute."""
        static_id = f"{cls}.{attr}" if cls else attr
        if static_id in LOCK_REGISTRY:
            return LOCK_REGISTRY[static_id]
        decl = self.lock_decls.get((cls or "", attr))
        if decl is not None and decl.wrap_name:
            return decl.wrap_name
        if wrap_name:
            return wrap_name
        return static_id

    # -- class/method lookup ------------------------------------------

    def method_of(self, cls: str, name: str,
                  _seen: Optional[Set[str]] = None) -> Optional[str]:
        """fqn of ``cls.name``, walking base classes."""
        seen = _seen or set()
        if cls in seen:
            return None
        seen.add(cls)
        info = self.classes.get(cls)
        if info is None:
            return None
        if name in info.methods:
            return info.methods[name]
        for b in info.bases:
            got = self.method_of(b, name, seen)
            if got:
                return got
        return None

    def subclasses_of(self, cls: str) -> List[str]:
        out = []
        for name, info in self.classes.items():
            if cls in info.bases:
                out.append(name)
                out.extend(self.subclasses_of(name))
        return out

    def declaring_classes(self, attr: str) -> List[str]:
        return self.lock_attr_classes.get(attr, [])


# ---------------------------------------------------------------------
# pass 1: indexes (classes, methods, lock decls, attr types)
# ---------------------------------------------------------------------

def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _IndexVisitor(ast.NodeVisitor):
    def __init__(self, model: ProgramModel, relpath: str) -> None:
        self.m = model
        self.relpath = relpath
        self._cls: List[str] = []
        self._def: List[str] = []

    def _fqn(self, name: str) -> str:
        qual = ".".join(self._cls + self._def + [name])
        return f"{self.relpath}::{qual}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = tuple(b for b in (_tail(x) for x in node.bases) if b)
        # Innermost class wins for nested classes (handler-in-closure).
        self.m.classes.setdefault(
            node.name, ClassInfo(node.name, self.relpath, bases))
        self._cls.append(node.name)
        saved, self._def = self._def, []
        self.generic_visit(node)
        self._def = saved
        self._cls.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        fqn = self._fqn(node.name)
        cls = self._cls[-1] if self._cls and not self._def else None
        qual = ".".join(self._cls + self._def + [node.name])
        self.m.functions[fqn] = FuncInfo(
            fqn=fqn, qual=qual, name=node.name, cls=cls,
            relpath=self.relpath, node=node)
        if cls is not None:
            self.m.classes[cls].methods.setdefault(node.name, fqn)
        self.m.module_funcs.setdefault(
            (self.relpath, node.name), []).append(fqn)
        if cls is not None:
            self._scan_decls(node, cls)
        self._def.append(node.name)
        self.generic_visit(node)
        self._def.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _scan_decls(self, fn: ast.FunctionDef, cls: str) -> None:
        """Lock declarations + attr types from ``self.X = ...``."""
        for st in ast.walk(fn):
            if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                continue
            tgt = st.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            wrap_name: Optional[str] = None
            is_lock = False
            first_cls: Optional[str] = None
            for sub in ast.walk(st.value):
                if not isinstance(sub, ast.Call):
                    continue
                t = _tail(sub.func)
                if t in _LOCK_FACTORIES:
                    is_lock = True
                elif t == "wrap" and sub.args and isinstance(
                        sub.args[0], ast.Constant) and isinstance(
                        sub.args[0].value, str):
                    is_lock = True
                    wrap_name = sub.args[0].value
                elif (t and first_cls is None and t in self.m.classes
                      ) or (t and first_cls is None and t[:1].isupper()):
                    first_cls = t
            if is_lock:
                key = (cls, attr)
                if key not in self.m.lock_decls or wrap_name:
                    self.m.lock_decls[key] = LockDecl(
                        cls, attr, self.relpath, st.lineno, wrap_name)
                    lst = self.m.lock_attr_classes.setdefault(attr, [])
                    if cls not in lst:
                        lst.append(cls)
            elif first_cls is not None:
                self.m.attr_types.setdefault((cls, attr), first_cls)


# ---------------------------------------------------------------------
# pass 2: per-function lexical walk (held sets, acqs, calls, writes)
# ---------------------------------------------------------------------

def _call_blocking(call: ast.Call) -> bool:
    """Is ``lock.acquire(...)`` an unbounded blocking acquisition?"""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return False
        if len(call.args) > 1:       # acquire(True, timeout)
            a1 = call.args[1]
            if not (isinstance(a1, ast.Constant)
                    and isinstance(a1.value, (int, float))
                    and a1.value < 0):
                return False
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(
                kw.value, ast.Constant) and kw.value.value is False:
            return False
        if kw.arg == "timeout":
            if not (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, (int, float))
                    and kw.value.value < 0):
                return False
    return True


class _BodyWalker:
    def __init__(self, model: ProgramModel, fi: FuncInfo) -> None:
        self.m = model
        self.fi = fi

    def run(self) -> None:
        node = self.fi.node
        held: List[str] = []
        self._stmts(node.body, held)

    # -- receiver typing ----------------------------------------------

    def _receiver_class(self, expr: ast.AST) -> Optional[str]:
        d = dotted_name(expr)
        if d is None:
            return None
        parts = d.split(".")
        if parts[0] == "self":
            if self.fi.cls is None:
                return None
            cur: Optional[str] = self.fi.cls
            rest = parts[1:]
        else:
            cur = RECEIVER_TYPES.get(parts[0])
            if cur is None and parts[0] in self.m.classes:
                cur = parts[0]       # ClassName.method style
            if cur is None:
                return None
            rest = parts[1:]
        for attr in rest:
            nxt = self._attr_type(cur, attr)
            if nxt is None:
                nxt = RECEIVER_TYPES.get(attr)
            if nxt is None:
                return None
            cur = nxt
        return cur

    def _attr_type(self, cls: str, attr: str,
                   _seen: Optional[Set[str]] = None) -> Optional[str]:
        seen = _seen or set()
        if cls in seen:
            return None
        seen.add(cls)
        got = self.m.attr_types.get((cls, attr))
        if got:
            return got
        info = self.m.classes.get(cls)
        if info:
            for b in info.bases:
                got = self._attr_type(b, attr, seen)
                if got:
                    return got
        return None

    # -- lock site resolution -----------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        """Canonical lock name for a ``with X`` item / ``X.acquire()``
        receiver, or None if X is not a known lock."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            owner = self._receiver_class(expr.value)
        elif isinstance(expr, ast.Name):
            attr, owner = expr.id, None
        else:
            return None
        if owner is not None:
            cur: Optional[str] = owner
            seen: Set[str] = set()
            while cur and cur not in seen:
                seen.add(cur)
                if (cur, attr) in self.m.lock_decls:
                    return self.m.canon_lock(cur, attr)
                info = self.m.classes.get(cur)
                cur = info.bases[0] if info and info.bases else None
            if _LOCK_NAME.search(attr):
                return self.m.canon_lock(owner, attr)
            return None
        declaring = self.m.declaring_classes(attr)
        if len(declaring) == 1:
            return self.m.canon_lock(declaring[0], attr)
        if len(declaring) > 1:
            same_file = [c for c in declaring
                         if self.m.classes[c].relpath == self.fi.relpath]
            if len(same_file) == 1:
                return self.m.canon_lock(same_file[0], attr)
            return self.m.canon_lock(sorted(declaring)[0], attr)
        if _LOCK_NAME.search(attr):
            return self.m.canon_lock(self.fi.cls, attr)
        return None

    # -- call target resolution ---------------------------------------

    def _resolve_call(self, call: ast.Call) -> Tuple[str, ...]:
        fn = call.func
        t = _tail(fn)
        if t is None:
            return ()
        if isinstance(fn, ast.Name):
            # Class instantiation -> __init__.
            if t in self.m.classes:
                init = self.m.method_of(t, "__init__")
                return (init,) if init else ()
            # Local / module-level function in the same module.
            cands = self.m.module_funcs.get((self.fi.relpath, t), [])
            if cands:
                # Prefer one nested inside the current def chain.
                prefix = f"{self.fi.relpath}::{self.fi.qual}."
                nested = [c for c in cands if c.startswith(prefix)]
                return tuple(nested or cands[:1])
            return ()
        # Attribute call: type the receiver.
        owner = self._receiver_class(fn.value)
        if owner is None and t in self.m.classes:
            init = self.m.method_of(t, "__init__")
            return (init,) if init else ()
        if owner is not None:
            out: List[str] = []
            got = self.m.method_of(owner, t)
            if got:
                out.append(got)
            for sub in self.m.subclasses_of(owner):
                sm = self.m.classes[sub].methods.get(t)
                if sm:
                    out.append(sm)
            if not out:
                self.m.unresolved_calls += 1
            return tuple(dict.fromkeys(out))
        # Unknown receiver: link only if exactly one program class
        # defines the method (avoids stdlib-name collisions), and
        # never for names that shadow builtin-collection APIs.
        if t in _GENERIC_METHODS:
            self.m.unresolved_calls += 1
            return ()
        definers = [c for c in self.m.classes.values() if t in c.methods]
        if len(definers) == 1:
            cls = definers[0]
            out = [cls.methods[t]]
            for sub in self.m.subclasses_of(cls.name):
                sm = self.m.classes[sub].methods.get(t)
                if sm:
                    out.append(sm)
            return tuple(dict.fromkeys(out))
        self.m.unresolved_calls += 1
        return ()

    # -- statement walk ------------------------------------------------

    def _stmts(self, body: Sequence[ast.stmt], held: List[str]) -> None:
        for st in body:
            self._stmt(st, held)

    def _stmt(self, st: ast.stmt, held: List[str]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                      # walked as their own FuncInfos
        if isinstance(st, (ast.With, ast.AsyncWith)):
            taken: List[str] = []
            for item in st.items:
                self._expr(item.context_expr, held)
                lk = self._resolve_lock(item.context_expr)
                if lk is not None:
                    self._acquire(lk, item.context_expr.lineno, True, held)
                    held.append(lk)
                    taken.append(lk)
            self._stmts(st.body, held)
            for _ in taken:
                held.pop()
            return
        if isinstance(st, ast.Try):
            # try/finally flows acquire/release effects through: the
            # acquire-in-try / release-in-finally idiom must leave the
            # held set balanced after the statement.
            self._stmts(st.body, held)
            for h in st.handlers:
                self._stmts(h.body, list(held))
            self._stmts(st.orelse, list(held))
            self._stmts(st.finalbody, held)
            return
        if isinstance(st, ast.If):
            self._expr(st.test, held)   # `if not x.acquire(False):`
            self._stmts(st.body, list(held))
            self._stmts(st.orelse, list(held))
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, held)
            self._stmts(st.body, list(held))
            self._stmts(st.orelse, list(held))
            return
        if isinstance(st, ast.While):
            self._expr(st.test, held)
            self._stmts(st.body, list(held))
            self._stmts(st.orelse, list(held))
            return
        # Simple statements: writes + expression scan.
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for tgt in targets:
                self._write_target(tgt, st.lineno, held)
            if getattr(st, "value", None) is not None:
                self._expr(st.value, held)
            return
        if isinstance(st, ast.Delete):
            for tgt in st.targets:
                self._write_target(tgt, st.lineno, held)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    # -- expression scan -----------------------------------------------

    def _expr(self, node: ast.AST, held: List[str]) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return                      # deferred execution
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, held)

    def _call(self, call: ast.Call, held: List[str]) -> None:
        fn = call.func
        # Arguments first (inner calls run before the outer one).
        for a in call.args:
            self._expr(a, held)
        for kw in call.keywords:
            self._expr(kw.value, held)
        if isinstance(fn, ast.Attribute) and fn.attr in ("acquire",
                                                         "release"):
            lk = self._resolve_lock(fn.value)
            if lk is not None:
                if fn.attr == "acquire":
                    self._acquire(lk, call.lineno, _call_blocking(call),
                                  held)
                    held.append(lk)
                else:
                    # Remove the most recent matching hold.
                    for i in range(len(held) - 1, -1, -1):
                        if held[i] == lk:
                            del held[i]
                            break
                return
        t = _tail(fn)
        if t in ("Thread", "Timer"):
            self._spawn(call, t)
            return
        if (isinstance(fn, ast.Attribute) and t in _MUTATORS
                and isinstance(fn.value, (ast.Attribute, ast.Subscript))):
            base = fn.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute):
                self._record_write(base, call.lineno, held)
        targets = self._resolve_call(call)
        if targets:
            self.fi.calls.append(
                CallSite(call.lineno, tuple(held), targets))
        if isinstance(fn, ast.Attribute):
            self._expr(fn.value, held)

    # -- recording -----------------------------------------------------

    def _acquire(self, canon: str, line: int, blocking: bool,
                 held: List[str]) -> None:
        self.fi.acquisitions.append(
            Acq(canon, line, blocking, tuple(held)))

    def _write_target(self, tgt: ast.AST, line: int,
                      held: List[str]) -> None:
        while isinstance(tgt, (ast.Subscript, ast.Starred)):
            tgt = tgt.value
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._write_target(el, line, held)
            return
        if isinstance(tgt, ast.Attribute):
            self._record_write(tgt, line, held)

    def _record_write(self, attr_node: ast.Attribute, line: int,
                      held: List[str]) -> None:
        owner = self._receiver_class(attr_node.value)
        if owner is None:
            return
        attr = attr_node.attr
        if (owner, attr) in self.m.lock_decls:
            return                      # lock rebinding, not shared data
        self.fi.writes.append(WriteSite(
            owner, attr, line, tuple(held), self.fi.qual,
            self.fi.relpath))

    def _spawn(self, call: ast.Call, kind: str) -> None:
        target_expr: Optional[ast.AST] = None
        tname: Optional[str] = None
        if kind == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                elif kw.arg == "name" and isinstance(
                        kw.value, ast.Constant):
                    tname = str(kw.value.value)
        elif len(call.args) >= 2:       # Timer(interval, fn)
            target_expr = call.args[1]
        fqn = self._resolve_target_fqn(target_expr)
        self.fi.spawns.append(ThreadSpawn(
            call.lineno, fqn, tname, self.fi.relpath, self.fi.qual))

    def _resolve_target_fqn(self,
                            expr: Optional[ast.AST]) -> Optional[str]:
        if expr is None:
            return None
        if isinstance(expr, ast.Name):
            cands = self.m.module_funcs.get(
                (self.fi.relpath, expr.id), [])
            prefix = f"{self.fi.relpath}::{self.fi.qual}."
            nested = [c for c in cands if c.startswith(prefix)]
            return (nested or cands or [None])[0]
        if isinstance(expr, ast.Attribute):
            owner = self._receiver_class(expr.value)
            if owner is not None:
                return self.m.method_of(owner, expr.attr)
        return None


# ---------------------------------------------------------------------
# model + graph construction
# ---------------------------------------------------------------------

def build_model(sources: Dict[str, str]) -> ProgramModel:
    """Parse the program file set ({relpath: source}) into a model."""
    model = ProgramModel()
    trees: Dict[str, ast.Module] = {}
    for relpath in sorted(sources):
        tree = ast.parse(sources[relpath])
        trees[relpath] = tree
        model.sources[relpath] = sources[relpath].splitlines()
        _IndexVisitor(model, relpath).visit(tree)
    for fi in model.functions.values():
        _BodyWalker(model, fi).run()
    return model


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    blocking: bool = False
    # Witness: list of (relpath, func-qual, line, note) frames from
    # the holder of `src` down to the acquisition of `dst`.
    witness: Tuple[Tuple[str, str, int, str], ...] = ()


class LockGraph:
    def __init__(self, model: ProgramModel) -> None:
        self.model = model
        self.edges: Dict[Tuple[str, str], Edge] = {}

    def _add(self, src: str, dst: str, blocking: bool,
             witness: Tuple[Tuple[str, str, int, str], ...]) -> None:
        key = (src, dst)
        e = self.edges.get(key)
        if e is None:
            self.edges[key] = Edge(src, dst, blocking, witness)
        elif blocking and not e.blocking:
            # Upgrade to a blocking witness — cycles only form over
            # blocking edges, so keep the witness that proves one.
            e.blocking = True
            e.witness = witness

    def edge_names(self) -> Set[str]:
        return {f"{a}->{b}" for (a, b) in self.edges}

    def nodes(self) -> Set[str]:
        out: Set[str] = set()
        for a, b in self.edges:
            out.add(a)
            out.add(b)
        return out


def build_lock_graph(model: ProgramModel) -> LockGraph:
    g = LockGraph(model)
    # 1. Lexical edges.
    for fi in model.functions.values():
        for acq in fi.acquisitions:
            for h in acq.held:
                if h != acq.canon:
                    g._add(h, acq.canon, acq.blocking,
                           ((fi.relpath, fi.qual, acq.line,
                             f"acquires {acq.canon} holding {h}"),))
    # 2. Transitive acquisition sets (may-analysis, to fixpoint).
    #    acq_star[fqn] = {canon: (blocking_any, origin)} where origin
    #    is ("direct", line) or ("call", line, callee_fqn).
    acq_star: Dict[str, Dict[str, Tuple[bool, tuple]]] = {
        fqn: {} for fqn in model.functions}
    for fqn, fi in model.functions.items():
        for acq in fi.acquisitions:
            cur = acq_star[fqn].get(acq.canon)
            if cur is None or (acq.blocking and not cur[0]):
                acq_star[fqn][acq.canon] = (
                    acq.blocking, ("direct", acq.line))
    changed = True
    while changed:
        changed = False
        for fqn, fi in model.functions.items():
            mine = acq_star[fqn]
            for cs in fi.calls:
                for t in cs.targets:
                    for canon, (blk, _origin) in acq_star.get(
                            t, {}).items():
                        cur = mine.get(canon)
                        if cur is None or (blk and not cur[0]):
                            mine[canon] = (blk, ("call", cs.line, t))
                            changed = True
    # 3. Call edges: held at a call site -> anything the callee (or
    #    its callees) may acquire.
    def chain(fqn: str, canon: str,
              depth: int = 0) -> Tuple[Tuple[str, str, int, str], ...]:
        fi = model.functions[fqn]
        if depth > 24:
            return ((fi.relpath, fi.qual, 0, "..."),)
        entry = acq_star[fqn].get(canon)
        if entry is None:
            return ()
        _blk, origin = entry
        if origin[0] == "direct":
            return ((fi.relpath, fi.qual, origin[1],
                     f"acquires {canon}"),)
        _tag, line, callee = origin
        callee_qual = model.functions[callee].qual
        return ((fi.relpath, fi.qual, line, f"calls {callee_qual}"),
                ) + chain(callee, canon, depth + 1)

    for fqn, fi in model.functions.items():
        for cs in fi.calls:
            if not cs.held:
                continue
            for t in cs.targets:
                for canon, (blk, _origin) in acq_star.get(t, {}).items():
                    for h in cs.held:
                        if h == canon:
                            continue
                        key = (h, canon)
                        e = g.edges.get(key)
                        if e is not None and (e.blocking or not blk):
                            continue
                        callee_qual = model.functions[t].qual
                        wit = ((fi.relpath, fi.qual, cs.line,
                                f"holding {h}, calls {callee_qual}"),
                               ) + chain(t, canon)
                        g._add(h, canon, blk, wit)
    return g


# ---------------------------------------------------------------------
# cycle detection -> findings
# ---------------------------------------------------------------------

def _blocking_cycles(g: LockGraph) -> List[List[str]]:
    """Minimal node cycles over blocking edges, one per SCC."""
    adj: Dict[str, List[str]] = {}
    for (a, b), e in g.edges.items():
        if e.blocking:
            adj.setdefault(a, []).append(b)
    # Tarjan SCC (iterative).
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(adj.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

    for v in list(adj):
        if v not in index:
            strongconnect(v)

    cycles: List[List[str]] = []
    for comp in sccs:
        compset = set(comp)
        has_cycle = len(comp) > 1 or any(
            v in adj.get(v, ()) for v in comp)
        if not has_cycle:
            continue
        start = min(comp)
        if start in adj.get(start, ()):
            cycles.append([start, start])
            continue
        # BFS within the SCC back to `start`.
        prev: Dict[str, str] = {}
        queue = [start]
        found: Optional[str] = None
        seen = {start}
        while queue and found is None:
            v = queue.pop(0)
            for w in adj.get(v, ()):
                if w == start:
                    found = v
                    break
                if w in compset and w not in seen:
                    seen.add(w)
                    prev[w] = v
                    queue.append(w)
        if found is None:
            continue
        path = [found]
        while path[-1] != start:
            path.append(prev[path[-1]])
        path.reverse()
        cycles.append(path + [start])
    return cycles


def _fmt_witness(e: Edge) -> str:
    frames = " ; ".join(
        f"{rel}:{line} {qual} ({note})"
        for rel, qual, line, note in e.witness)
    return f"{e.src} -> {e.dst}: {frames}"


def lock_order_findings(g: LockGraph) -> List[Finding]:
    out: List[Finding] = []
    for cyc in _blocking_cycles(g):
        edges = [g.edges[(cyc[i], cyc[i + 1])]
                 for i in range(len(cyc) - 1)]
        first = edges[0]
        rel, qual, line, _note = first.witness[0] if first.witness else (
            "<unknown>", "<unknown>", 0, "")
        code = " -> ".join(cyc)
        msg = ("potential deadlock: lock-acquisition cycle "
               + " -> ".join(cyc) + ". "
               + " || ".join(_fmt_witness(e) for e in edges))
        out.append(Finding(
            rule="LOCK-ORDER", path=rel, line=line, func=qual,
            code=code, message=msg))
    out.sort(key=lambda f: f.sort_key())
    return out


# ---------------------------------------------------------------------
# canonical artifact (analysis/lockorder.json)
# ---------------------------------------------------------------------

def canonical_graph(g: LockGraph) -> Dict[str, object]:
    """Line-number-free canonical form of the static graph — the
    committed, reviewed lock-order artifact.  Sorted and stable so
    PR diffs show exactly the ordering edges that changed."""
    edges = []
    for (a, b), e in sorted(g.edges.items()):
        edges.append({"from": a, "to": b,
                      "blocking": bool(e.blocking)})
    return {"nodes": sorted(g.nodes()), "edges": edges}


# ---------------------------------------------------------------------
# checker entry point (program analysis)
# ---------------------------------------------------------------------

def analyze(sources: Dict[str, str]) -> List[Finding]:
    """LOCK-ORDER program analysis over the in-scope file set."""
    model = build_model(sources)
    return lock_order_findings(build_lock_graph(model))
